"""The RTC rule set: concurrency static analysis over ray_tpu's OWN tree.

The reference runtime's C++ planes are watched by TSan/ASan and
clang-tidy; our Python planes had no equivalent, and each of the last
few PRs shipped a cross-thread bug that only the chaos battery caught.
These rules are that missing pass.  Unlike the RTL rules (user-facing
API misuse), RTC targets the internals: classes holding
``threading.Lock``s, worker threads, callback registration, and the
package-wide order in which locks nest.

    RTC101  lock-discipline inference: an attribute written both under
            ``with self._lock`` and bare, in a class with a thread entry
    RTC102  lock-order cycle: the whole-package acquired-while-held
            graph contains a cycle (potential deadlock); the finding
            carries both witness paths (package-scope rule)
    RTC103  blocking under a lock: ray_tpu.get/wait, time.sleep,
            subprocess, Event.wait, Thread.join, or Condition.wait on a
            *different* lock while a lock is held
    RTC104  thread escape: a class spawns a thread on one of its own
            methods, has no lock at all, and mutates ``self`` outside
            ``__init__``

Static limits (documented, not silent): acquisition is recognized in
``with`` form only (manual ``.acquire()``/``.release()`` pairs are
invisible); lock identity is per *class attribute* (two instances of
one class are one node in the order graph — RLock-style reentrancy on
the same key is skipped); call-graph resolution covers ``self.m()``,
same-module ``f()``, and ``mod.f()`` for modules in the linted set.
The runtime complement (`ray_tpu._private.locksan`) records the REAL
acquisition order under the chaos battery and reports both order
violations and edges this analyzer missed.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu.lint import (Finding, ModuleContext, PackageRule, Rule,
                          register_package_rule, register_rule)

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# threading.<ctor> / locksan.<factory> spellings that mint a lock.
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock",
               "Condition": "condition", "Semaphore": "lock",
               "BoundedSemaphore": "lock"}
_LOCKSAN_CTORS = {"make_lock": "lock", "make_rlock": "rlock",
                  "make_condition": "condition"}
_THREAD_CTORS = {"Thread", "Timer"}
# with-used self attributes whose NAME alone marks them lock-like (for
# locks handed in via parameters rather than constructed in the class).
_LOCKISH_NAMES = ("lock", "mutex", "cond", "cv", "sem")

# self.<attr>.<m>(...) calls that mutate the container bound at <attr>.
_MUTATORS = {"append", "appendleft", "add", "extend", "insert", "update",
             "setdefault", "pop", "popleft", "popitem", "remove",
             "discard", "clear"}

# Callback registrars whose self-method argument marks the class as
# entered by another thread / event loop.
_CB_REGISTRARS = {"call_soon_threadsafe", "run_in_executor",
                  "add_done_callback", "register_at_fork"}


def _modbase(path: str) -> str:
    base = os.path.basename(path)
    if base == "__init__.py":
        base = os.path.basename(os.path.dirname(path)) or base
    return base[:-3] if base.endswith(".py") else base


def _is_self_attr(node) -> Optional[str]:
    """'x' for a `self.x` expression, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassConc:
    """Concurrency facts about one class."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.lock_attrs: Dict[str, str] = {}   # attr -> kind
        self.event_attrs: set = set()
        self.thread_attrs: set = set()
        # (ctor Call node, ("method", name) | ("local", name) | None,
        #  spawning method name)
        self.thread_sites: List[Tuple[ast.AST, Optional[tuple], str]] = []
        self.cb_sites: List[ast.AST] = []
        self.subclasses_thread = False
        # (attr, node, held frozenset, method name, in-closure flag).
        # The closure flag marks writes inside a nested def: those run
        # on whatever thread CALLS the closure, not on the thread
        # executing the enclosing method body.
        self.writes: List[Tuple[str, ast.AST, frozenset, str, bool]] = []
        # attr -> (node, lock keys held) of one guarded write (evidence
        # for the RTC101 message)
        self.guarded_sites: Dict[str, Tuple[ast.AST, frozenset]] = {}

    @property
    def threaded(self) -> bool:
        return bool(self.thread_sites or self.cb_sites
                    or self.subclasses_thread)


class _ModuleConc:
    """One module's concurrency analysis: per-class discipline facts,
    the local acquired-while-held edges, per-function acquisition
    summaries, and blocking-under-lock hits."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.path = ctx.path
        self.modbase = _modbase(ctx.path)
        # import alias maps
        self.threading_aliases: set = set()
        self.time_aliases: set = set()
        self.subprocess_aliases: set = set()
        self.select_aliases: set = set()
        self.locksan_aliases: set = set()
        self.from_threading: Dict[str, str] = {}   # local -> ctor name
        self.from_time_sleep: set = set()
        self.import_mods: Dict[str, str] = {}      # alias -> module base
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.module_locks: Dict[str, str] = {}     # name -> kind
        self.module_funcs: set = set()
        self.classes: Dict[str, _ClassConc] = {}
        # package-rule raw material
        self.edges: List[list] = []        # [a, b, line, desc]
        self.acquires: Dict[str, List[list]] = {}   # qual -> [[key, line]]
        self.calls: Dict[str, List[list]] = {}      # qual -> [ref...]
        self.held_calls: List[list] = []   # [held key, ref, line]
        # (node, message) RTC103 hits
        self.blocking: List[Tuple[ast.AST, str]] = []
        self._scan_imports()
        self._scan_module_scope()
        self._scan_classes()
        self._walk_all()

    # ------------------------------------------------------------ imports
    def _scan_imports(self):
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    leaf = alias.name.split(".")[-1]
                    bound = alias.asname or root
                    if alias.asname is None and "." in alias.name:
                        # `import a.b.c` binds `a`; attribute calls on
                        # the dotted tail are not tracked.
                        leaf = root
                    if leaf == "threading" or root == "threading":
                        self.threading_aliases.add(bound)
                    elif leaf == "time":
                        self.time_aliases.add(bound)
                    elif leaf == "subprocess":
                        self.subprocess_aliases.add(bound)
                    elif leaf == "select":
                        self.select_aliases.add(bound)
                    elif leaf == "locksan":
                        self.locksan_aliases.add(bound)
                    if root == "ray_tpu":
                        self.import_mods[bound] = alias.name.split(".")[-1]
                    elif "." not in alias.name:
                        # Plain `import m` of a sibling module: calls
                        # through it resolve when m is in the lint
                        # scope (stdlib modules contribute no acquires
                        # to the graph, so this is harmless for them).
                        self.import_mods.setdefault(bound, alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module
                leaf = mod.split(".")[-1]
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if mod == "threading":
                        self.from_threading[bound] = alias.name
                    elif mod == "time" and alias.name == "sleep":
                        self.from_time_sleep.add(bound)
                    if mod.startswith("ray_tpu"):
                        # from ray_tpu._private import tracing as _t
                        # -> _t aliases module "tracing"; from
                        # ray_tpu.x.y import f -> f is y's function.
                        self.import_mods.setdefault(bound, alias.name)
                        self.from_imports[bound] = (leaf, alias.name)
                        if alias.name == "locksan":
                            self.locksan_aliases.add(bound)

    # ----------------------------------------------------- ctor detection
    def _ctor_kind(self, call: ast.Call, table: Dict[str, str],
                   names: Optional[set] = None) -> Optional[str]:
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            root = fn.value.id
            if root in self.threading_aliases and fn.attr in table:
                return table[fn.attr]
            if root in self.locksan_aliases and \
                    fn.attr in _LOCKSAN_CTORS and table is _LOCK_CTORS:
                return _LOCKSAN_CTORS[fn.attr]
            if names is not None and root in self.threading_aliases \
                    and fn.attr in names:
                return fn.attr
        elif isinstance(fn, ast.Name):
            tgt = self.from_threading.get(fn.id)
            if tgt is not None:
                if tgt in table:
                    return table[tgt]
                if names is not None and tgt in names:
                    return tgt
            tgt2 = self.from_imports.get(fn.id)
            if tgt2 is not None and tgt2[1] in _LOCKSAN_CTORS \
                    and table is _LOCK_CTORS:
                return _LOCKSAN_CTORS[tgt2[1]]
        return None

    def _lock_ctor(self, call) -> Optional[str]:
        return self._ctor_kind(call, _LOCK_CTORS)

    def _event_ctor(self, call) -> bool:
        return self._ctor_kind(call, {"Event": "event"}) == "event"

    def _thread_ctor(self, call) -> Optional[str]:
        return self._ctor_kind(call, {}, names=_THREAD_CTORS)

    # -------------------------------------------------------- module scope
    def _scan_module_scope(self):
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, _DEFS):
                self.module_funcs.add(stmt.name)
            elif isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                kind = self._lock_ctor(stmt.value)
                if kind is not None:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self.module_locks[tgt.id] = kind

    # ------------------------------------------------------------- classes
    def _scan_classes(self):
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cls = _ClassConc(node)
            self.classes[node.name] = cls
            for base in node.bases:
                tail = base
                while isinstance(tail, ast.Attribute):
                    if tail.attr == "Thread":
                        cls.subclasses_thread = True
                    tail = tail.value
                if isinstance(tail, ast.Name) and \
                        self.from_threading.get(tail.id) == "Thread":
                    cls.subclasses_thread = True
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Call):
                    attr = None
                    for tgt in sub.targets:
                        a = _is_self_attr(tgt)
                        if a is not None:
                            attr = a
                    if attr is None:
                        continue
                    kind = self._lock_ctor(sub.value)
                    if kind is not None:
                        cls.lock_attrs[attr] = kind
                    elif self._event_ctor(sub.value):
                        cls.event_attrs.add(attr)
                    elif self._thread_ctor(sub.value) is not None:
                        cls.thread_attrs.add(attr)
            # A with-used lock-named attribute counts as a lock even
            # when it was handed in (not constructed here).  Sync
            # `with` only: `async with self._cond` is an asyncio
            # primitive — the event loop already serializes bare
            # access, so it stays out of the THREAD-lock analysis.
            for sub in ast.walk(node):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        a = _is_self_attr(item.context_expr)
                        if a is not None and a not in cls.lock_attrs \
                                and any(t in a.lower()
                                        for t in _LOCKISH_NAMES):
                            cls.lock_attrs[a] = "lock"

    # ------------------------------------------------------------ the walk
    def _walk_all(self):
        body_defs = []
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, _DEFS):
                body_defs.append((stmt, None,
                                  f"{self.modbase}.{stmt.name}"))
            elif isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, _DEFS):
                        body_defs.append(
                            (sub, self.classes[stmt.name],
                             f"{self.modbase}.{stmt.name}.{sub.name}"))
        for fn, cls, qual in body_defs:
            self._walk_fn(fn, cls, qual, fn.name, closure=False)

    def _walk_fn(self, fn, cls, qual: str, method: str,
                 closure: bool):
        self.acquires.setdefault(qual, [])
        self.calls.setdefault(qual, [])
        use_cls = cls
        if cls is not None and not closure and not (
                fn.args.args and fn.args.args[0].arg == "self"):
            # No self receiver and not a closure: a static method has
            # no instance (closures DO — they capture self).
            use_cls = None
        held: tuple = ()
        if use_cls is not None and not closure and \
                method.endswith("_locked") and use_cls.lock_attrs:
            # Convention: a `_foo_locked` method documents that its
            # CALLER holds the class lock — analyze its body as if the
            # (single or first) class lock were held.
            held = (f"{use_cls.name}.{min(use_cls.lock_attrs)}",)
        for stmt in fn.body:
            self._walk_node(stmt, use_cls, qual, held, method, closure)

    def _lock_key(self, expr, cls) -> Optional[str]:
        a = _is_self_attr(expr)
        if a is not None and cls is not None and a in cls.lock_attrs:
            return f"{cls.name}.{a}"
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"{self.modbase}.{expr.id}"
        return None

    def _walk_node(self, node, cls, qual, held, method, closure):
        if isinstance(node, _DEFS):
            # A nested def's body does NOT run under the enclosing
            # with-block — fresh held set; it still belongs to the
            # method (closures capture self).
            self._walk_fn(node, cls, f"{qual}.{node.name}", method,
                          closure=True)
            return
        if isinstance(node, (ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                self._walk_node(item.context_expr, cls, qual,
                                tuple(inner), method, closure)
                key = self._lock_key(item.context_expr, cls)
                if key is not None:
                    for h in inner:
                        if h != key:
                            self.edges.append(
                                [h, key, node.lineno,
                                 f"{qual} acquires {key} while "
                                 f"holding {h}"])
                    self.acquires[qual].append([key, node.lineno])
                    inner.append(key)
            for stmt in node.body:
                self._walk_node(stmt, cls, qual, tuple(inner), method,
                                closure)
            return
        if isinstance(node, ast.Call):
            self._handle_call(node, cls, qual, held, method, closure)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                               ast.Delete)):
            self._handle_write(node, cls, held, method, closure)
        for child in ast.iter_child_nodes(node):
            self._walk_node(child, cls, qual, held, method, closure)

    # ------------------------------------------------------------- writes
    def _write_attr_of(self, tgt) -> Optional[str]:
        a = _is_self_attr(tgt)
        if a is not None:
            return a
        if isinstance(tgt, ast.Subscript):
            return _is_self_attr(tgt.value)
        return None

    def _handle_write(self, node, cls, held, method, closure):
        if cls is None:
            return
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            # `container[key] = self._method` registers a handler some
            # other thread (RPC dispatch, event loop) will call.
            if _is_self_attr(node.value) is not None and any(
                    isinstance(t, ast.Subscript) and
                    _is_self_attr(t.value) is None
                    for t in node.targets):
                cls.cb_sites.append(node)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                sub_targets = tgt.elts
            else:
                sub_targets = [tgt]
            for t in sub_targets:
                attr = self._write_attr_of(t)
                if attr is None or attr in cls.lock_attrs:
                    continue
                heldset = frozenset(held)
                cls.writes.append((attr, node, heldset, method, closure))
                if heldset and attr not in cls.guarded_sites:
                    cls.guarded_sites[attr] = (node, heldset)

    # -------------------------------------------------------------- calls
    def _callee_ref(self, call, cls) -> Optional[list]:
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            if fn.value.id == "self" and cls is not None:
                return ["self", self.modbase, cls.name, fn.attr]
            tgt = self.import_mods.get(fn.value.id)
            if tgt is not None:
                return ["mod", tgt, fn.attr]
        elif isinstance(fn, ast.Name):
            if fn.id in self.module_funcs:
                return ["mod", self.modbase, fn.id]
            tgt = self.from_imports.get(fn.id)
            if tgt is not None:
                return ["mod", tgt[0], tgt[1]]
        return None

    def _thread_target_of(self, call) -> Optional[tuple]:
        cands = [kw.value for kw in call.keywords if kw.arg == "target"]
        if not cands and len(call.args) >= 2:
            cands = [call.args[1]]  # Thread(group, target) / Timer(t, fn)
        for v in cands:
            a = _is_self_attr(v)
            if a is not None:
                return ("method", a)
            if isinstance(v, ast.Name):
                return ("local", v.id)
        return None

    def _handle_call(self, call, cls, qual, held, method, closure):
        ref = self._callee_ref(call, cls)
        if ref is not None:
            self.calls[qual].append(ref)
        fn = call.func
        if cls is not None:
            if self._thread_ctor(call) is not None:
                cls.thread_sites.append(
                    (call, self._thread_target_of(call), method))
            elif isinstance(fn, ast.Attribute) and \
                    fn.attr in _CB_REGISTRARS:
                for arg in list(call.args) + \
                        [kw.value for kw in call.keywords]:
                    if _is_self_attr(arg) is not None:
                        cls.cb_sites.append(call)
                        break
        # Container mutations count as attribute writes.  `.update()`
        # needs arguments: a no-arg update() is some OTHER protocol's
        # method (autoscaler.update()), not a dict merge.
        if cls is not None and isinstance(fn, ast.Attribute) and \
                fn.attr in _MUTATORS and not (
                    fn.attr == "update"
                    and not call.args and not call.keywords):
            attr = _is_self_attr(fn.value)
            if attr is not None and attr not in cls.lock_attrs:
                heldset = frozenset(held)
                cls.writes.append((attr, call, heldset, method, closure))
                if heldset and attr not in cls.guarded_sites:
                    cls.guarded_sites[attr] = (call, heldset)
        if not held:
            return
        if ref is not None:
            for h in held:
                self.held_calls.append([h, ref, call.lineno])
        msg = self._blocking_reason(call, cls, held)
        if msg is not None:
            self.blocking.append((call, msg))

    def _blocking_reason(self, call, cls, held) -> Optional[str]:
        api = self.ctx.api_call_name(call)
        hnames = ", ".join(sorted(held))
        if api in ("get", "wait"):
            return (f"ray_tpu.{api}() blocks on remote work while "
                    f"holding {hnames}; every other thread needing the "
                    "lock stalls behind the cluster round-trip")
        fn = call.func
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            root = fn.value.id
            if root in self.time_aliases and fn.attr == "sleep":
                return (f"time.sleep() parks the thread while holding "
                        f"{hnames}")
            if root in self.subprocess_aliases:
                return (f"subprocess.{fn.attr}() waits on a child "
                        f"process while holding {hnames}")
            if root in self.select_aliases and fn.attr == "select":
                return f"select.select() blocks while holding {hnames}"
        elif isinstance(fn, ast.Name) and fn.id in self.from_time_sleep:
            return f"time.sleep() parks the thread while holding {hnames}"
        # self.<attr>.wait()/join() where <attr> is a known sync object
        if isinstance(fn, ast.Attribute):
            owner = _is_self_attr(fn.value)
            if owner is not None and cls is not None:
                if fn.attr in ("wait", "wait_for") and \
                        cls.lock_attrs.get(owner) == "condition":
                    own = f"{cls.name}.{owner}"
                    others = [h for h in held if h != own]
                    if others:
                        return (f"Condition {owner}.wait() releases "
                                f"only its own lock; "
                                f"{', '.join(sorted(others))} stays "
                                "held for the whole wait")
                elif fn.attr == "wait" and owner in cls.event_attrs:
                    return (f"Event {owner}.wait() blocks while "
                            f"holding {hnames}")
                elif fn.attr == "join" and owner in cls.thread_attrs:
                    return (f"Thread {owner}.join() blocks while "
                            f"holding {hnames}; if that thread needs "
                            "the lock this deadlocks")
        return None


_INIT_METHODS = ("__init__", "__new__", "__post_init__")


def _analyze(ctx: ModuleContext) -> _ModuleConc:
    info = getattr(ctx, "_rtc_info", None)
    if info is None:
        info = _ModuleConc(ctx)
        ctx._rtc_info = info
    return info


# ==================================================== per-module rules

@register_rule
class LockDiscipline(Rule):
    code = "RTC101"
    name = "mixed-lock-discipline"
    severity = "warning"
    description = ("an attribute is written both under the class lock "
                   "and bare while the class has a thread entry point "
                   "— one of the two sides is a race")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        info = _analyze(ctx)
        for cls in info.classes.values():
            if not cls.threaded:
                continue
            per_attr: Dict[str, Dict[str, list]] = {}
            for attr, node, heldset, method, closure in cls.writes:
                if method in _INIT_METHODS and not closure:
                    continue
                slot = per_attr.setdefault(attr,
                                           {"bare": [], "guarded": []})
                slot["guarded" if heldset else "bare"].append(node)
            for attr in sorted(per_attr):
                slot = per_attr[attr]
                if not slot["bare"] or not slot["guarded"]:
                    continue
                bare = min(slot["bare"], key=lambda n: n.lineno)
                gnode, gheld = cls.guarded_sites.get(
                    attr, (slot["guarded"][0], frozenset()))
                locks = ", ".join(sorted(gheld)) or "the class lock"
                yield self.finding(
                    ctx, bare,
                    f"{cls.name}.{attr} is written here WITHOUT the "
                    f"lock, but under {locks} at line "
                    f"{gnode.lineno}; {cls.name} has a thread entry "
                    "point, so the bare write races the locked one — "
                    "take the lock here, or document single-thread "
                    "ownership with a noqa")


@register_rule
class BlockingUnderLock(Rule):
    code = "RTC103"
    name = "blocking-under-lock"
    severity = "warning"
    description = ("a blocking call (ray_tpu.get/wait, time.sleep, "
                   "subprocess, Event.wait, Thread.join, Condition."
                   "wait on a different lock) runs while a lock is "
                   "held — lock hold time becomes unbounded")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        info = _analyze(ctx)
        for node, msg in info.blocking:
            yield self.finding(ctx, node, msg)


@register_rule
class ThreadEscape(Rule):
    code = "RTC104"
    name = "thread-escape-unlocked"
    severity = "warning"
    description = ("a class spawns a thread on one of its own methods, "
                   "holds no lock at all, and mutates self outside "
                   "__init__ — the spawned thread and its creator "
                   "share unsynchronized state")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        info = _analyze(ctx)
        for cls in info.classes.values():
            if cls.lock_attrs or not cls.thread_sites:
                continue
            # Self-call graph within the class, to chase what the
            # thread's target method reaches (target -> helpers).
            calls_in: Dict[str, set] = {}
            for qual, refs in info.calls.items():
                parts = qual.split(".")
                if len(parts) >= 3 and parts[0] == info.modbase and \
                        parts[1] == cls.name:
                    slot = calls_in.setdefault(parts[2], set())
                    for ref in refs:
                        if ref[0] == "self" and ref[2] == cls.name:
                            slot.add(ref[3])
            for site, target, site_method in cls.thread_sites:
                if target is not None and target[0] == "method":
                    reach = {target[1]}
                    frontier = [target[1]]
                    while frontier:
                        for n in calls_in.get(frontier.pop(), ()):
                            if n not in reach:
                                reach.add(n)
                                frontier.append(n)
                    mutated = sorted(
                        {a for a, _n, _h, m, _c in cls.writes
                         if m in reach and m not in _INIT_METHODS})
                    tgt = f"self.{target[1]}"
                elif target is not None and target[0] == "local":
                    # A local closure: only its own writes run on the
                    # spawned thread; the enclosing method body's
                    # writes happen-before start().
                    mutated = sorted(
                        {a for a, _n, _h, m, c in cls.writes
                         if c and m == site_method})
                    tgt = f"local function {target[1]}"
                else:
                    mutated = sorted(
                        {a for a, _n, _h, m, c in cls.writes
                         if (m not in _INIT_METHODS
                             and m != site_method) or
                            (c and m == site_method)})
                    tgt = "a callable"
                if not mutated:
                    continue
                sample = ", ".join(f"self.{a}" for a in mutated[:3])
                yield self.finding(
                    ctx, site,
                    f"{cls.name} hands {tgt} to a new thread but "
                    f"defines no lock, and mutates {sample} outside "
                    "__init__ — writes from the spawned thread and "
                    "the owner interleave unsynchronized")
                break  # one finding per class


# ==================================================== package-scope rule

def _resolve(ref: list) -> str:
    if ref[0] == "self":
        return f"{ref[1]}.{ref[2]}.{ref[3]}"
    return f"{ref[1]}.{ref[2]}"


def build_lock_graph(summaries: List[dict]) -> Dict[str, Dict[str, dict]]:
    """Merge per-module summaries into the package acquired-while-held
    graph: {a: {b: {"path","line","desc"}}} meaning b was (or may be)
    acquired while a is held.  Shared with the runtime sanitizer's
    static-graph comparison (`--emit-lock-graph`)."""
    acq: Dict[str, Dict[str, dict]] = {}
    calls: Dict[str, List[list]] = {}
    adj: Dict[str, Dict[str, dict]] = {}

    def add_edge(a: str, b: str, prov: dict):
        if a == b:
            return  # reentrancy on one key (RLock style): not an order
        adj.setdefault(a, {}).setdefault(b, prov)

    for s in summaries:
        path = s["path"]
        for qual, pairs in s.get("acquires", {}).items():
            slot = acq.setdefault(qual, {})
            for key, line in pairs:
                slot.setdefault(key, {"path": path, "line": line,
                                      "desc": f"{qual} acquires {key}"})
        for qual, refs in s.get("calls", {}).items():
            calls.setdefault(qual, []).extend(refs)
        for a, b, line, desc in s.get("edges", []):
            add_edge(a, b, {"path": path, "line": line, "desc": desc})

    # Transitive closure of may-acquire over the resolvable call graph.
    changed = True
    passes = 0
    while changed and passes < 50:
        changed = False
        passes += 1
        for qual, refs in calls.items():
            slot = acq.setdefault(qual, {})
            for ref in refs:
                for key, prov in acq.get(_resolve(ref), {}).items():
                    if key not in slot:
                        slot[key] = prov
                        changed = True

    for s in summaries:
        path = s["path"]
        for held, ref, line in s.get("held_calls", []):
            callee = _resolve(ref)
            for key, prov in acq.get(callee, {}).items():
                add_edge(held, key, {
                    "path": path, "line": line,
                    "desc": (f"call to {callee}() while holding {held} "
                             f"reaches '{prov['desc']}' at "
                             f"{prov['path']}:{prov['line']}")})
    return adj


def _find_cycles(adj: Dict[str, Dict[str, dict]]
                 ) -> List[List[Tuple[str, str, dict]]]:
    """Cycles in the lock graph as edge lists [(a, b, prov), ...].
    Two-node cycles are enumerated exactly; longer cycles are found per
    SCC (one witness cycle per component)."""
    cycles = []
    covered = set()
    for a in sorted(adj):
        for b in sorted(adj[a]):
            if a < b and a in adj.get(b, {}):
                cycles.append([(a, b, adj[a][b]), (b, a, adj[b][a])])
                covered.update((a, b))
    # SCCs (iterative Tarjan) for >2-node cycles.
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: set = set()
    stack: List[str] = []
    counter = [0]
    sccs = []

    def strongconnect(v0):
        work = [(v0, iter(sorted(adj.get(v0, {}))))]
        index[v0] = low[v0] = counter[0]
        counter[0] += 1
        stack.append(v0)
        on_stack.add(v0)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, {})))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(set(adj) | {b for m in adj.values() for b in m}):
        if v not in index:
            strongconnect(v)

    for comp in sccs:
        if any(n in covered for n in comp) and len(comp) == 2:
            continue  # already reported as a 2-cycle
        comp_set = set(comp)
        # One witness cycle: DFS from the smallest node back to itself.
        start = comp[0]
        path: List[Tuple[str, str, dict]] = []

        def dfs(v, seen):
            for w in sorted(adj.get(v, {})):
                if w == start and path:
                    path.append((v, w, adj[v][w]))
                    return True
                if w in comp_set and w not in seen:
                    path.append((v, w, adj[v][w]))
                    if dfs(w, seen | {w}):
                        return True
                    path.pop()
            return False

        first = sorted(adj.get(start, {}))
        for w in first:
            if w in comp_set:
                path.append((start, w, adj[start][w]))
                if w == start or dfs(w, {start, w}):
                    break
                path.pop()
        if path and not any(set(e[:2]) <= covered
                            for e in path if len(set(e[:2])) == 2):
            cycles.append(path)
            covered.update(n for e in path for n in e[:2])
    return cycles


@register_package_rule
class LockOrderCycle(PackageRule):
    code = "RTC102"
    name = "lock-order-cycle"
    severity = "error"
    description = ("the package-wide acquired-while-held graph has a "
                   "cycle: two code paths take the same locks in "
                   "opposite orders, so the right interleaving "
                   "deadlocks both")

    def summarize(self, ctx: ModuleContext) -> dict:
        info = _analyze(ctx)
        return {"path": info.path,
                "edges": info.edges,
                "acquires": info.acquires,
                "calls": info.calls,
                "held_calls": info.held_calls}

    def check_package(self, summaries: List[dict]) -> Iterable[Finding]:
        adj = build_lock_graph(summaries)
        for cycle in _find_cycles(adj):
            a, b, prov = cycle[0]
            chain = " -> ".join([e[0] for e in cycle] + [cycle[0][0]])
            witnesses = "; ".join(
                f"[{e[0]} -> {e[1]}] {e[2]['desc']} "
                f"({e[2]['path']}:{e[2]['line']})" for e in cycle)
            yield Finding(
                code=self.code, severity=self.severity,
                path=prov["path"], line=prov["line"], col=0,
                message=(f"lock-order cycle {chain}: the same locks "
                         f"are taken in opposite orders — witness "
                         f"paths: {witnesses}"))


def emit_lock_graph(summaries: List[dict]) -> dict:
    """The statically derived order graph in the shape
    ``locksan.load_static_graph`` consumes: {"edges": [[a, b], ...]}."""
    adj = build_lock_graph(summaries)
    return {"edges": sorted([a, b] for a in adj for b in adj[a]),
            "comment": "ray_tpu.lint RTC102 acquired-while-held graph; "
                       "regenerate with --emit-lock-graph"}
