"""`python -m ray_tpu.lint <paths>` — run the distributed-correctness
linter and exit non-zero on NEW (non-baselined) findings.

The default baseline is `.rtlint-baseline.json` in the current
directory when present; `--no-baseline` ignores it, `--write-baseline`
regenerates it from the current findings (the adoption workflow:
baseline the backlog once, keep every new finding at zero).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ray_tpu.lint import (all_package_rules, all_rules, apply_baseline,
                          lint_paths, load_baseline, write_baseline)

DEFAULT_BASELINE = ".rtlint-baseline.json"

_SARIF_LEVEL = {"error": "error", "warning": "warning"}


def _to_sarif(findings) -> dict:
    """Minimal SARIF 2.1.0 run — enough for CI annotation uploads."""
    rules_meta = {}
    for code, cls in {**all_rules(), **all_package_rules()}.items():
        rules_meta[code] = {
            "id": code,
            "name": cls.name,
            "shortDescription": {"text": cls.description},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL.get(cls.severity, "warning")},
        }
    used = sorted({f.code for f in findings})
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "ray_tpu.lint",
                "informationUri": "https://example.invalid/ray_tpu",
                "rules": [rules_meta[c] for c in used
                          if c in rules_meta],
            }},
            "results": [{
                "ruleId": f.code,
                "level": _SARIF_LEVEL.get(f.severity, "warning"),
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace(os.sep, "/")},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col + 1)},
                }}],
            } for f in findings],
        }],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.lint",
        description="AST-based distributed-correctness linter for "
                    "ray_tpu programs")
    p.add_argument("paths", nargs="*", default=["."],
                   help="files or directories to lint")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default: {DEFAULT_BASELINE} "
                        "in the current directory, when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report all findings, ignoring any baseline")
    p.add_argument("--strict-reasons", action="store_true",
                   help="honor baseline entries ONLY for keys that "
                        "carry a justification string in the "
                        "baseline's \"reasons\" map (the nightly "
                        "strict mode: an unjustified count bump "
                        "fails)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings as the baseline "
                        "and exit 0")
    p.add_argument("--select", default=None,
                   help="comma-separated rule codes to run "
                        "(default: all)")
    p.add_argument("--format", choices=["text", "json", "sarif"],
                   default="text")
    p.add_argument("--jobs", "-j", type=int, default=1, metavar="N",
                   help="parse/lint N files in parallel (package-scope "
                        "rules still run once over the merged tree)")
    p.add_argument("--emit-lock-graph", default=None, metavar="PATH",
                   help="also write the RTC102 acquired-while-held "
                        "graph as JSON (consumed by the runtime "
                        "lock-order sanitizer; '-' for stdout)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule table and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        module_rules = sorted(all_rules().items())
        package_rules = sorted(all_package_rules().items())
        for code, cls in module_rules + package_rules:
            scope = " [package-scope]" if (code, cls) in package_rules \
                else ""
            print(f"{code}  {cls.severity:7s} {cls.name}: "
                  f"{cls.description}{scope}")
        return 0

    select = ({c.strip().upper() for c in args.select.split(",")}
              if args.select else None)
    findings = lint_paths(args.paths, select=select,
                          jobs=max(1, args.jobs))

    if args.emit_lock_graph is not None:
        from ray_tpu.lint import collect_summaries
        from ray_tpu.lint.concurrency import emit_lock_graph
        graph = emit_lock_graph(collect_summaries(args.paths))
        blob = json.dumps(graph, indent=2)
        if args.emit_lock_graph == "-":
            print(blob)
        else:
            with open(args.emit_lock_graph, "w") as f:
                f.write(blob + "\n")

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        if select:
            print("error: --write-baseline with --select would drop "
                  "every other rule's baselined findings; rerun "
                  "without --select", file=sys.stderr)
            return 2
        broken = [f for f in findings if f.code == "RTL000"]
        if broken:
            # Baselining a missing-path/unreadable finding would make
            # a typo'd lint target permanently green.
            for f in broken:
                print(f.format(), file=sys.stderr)
            print("error: refusing to write a baseline over "
                  "missing/unreadable paths", file=sys.stderr)
            return 2
        path = args.baseline or DEFAULT_BASELINE
        # Regenerate counts only for files under the scanned paths;
        # keys outside the scan scope are preserved, so a narrowed
        # invocation can't silently gut the checked-in baseline.
        preserve = {}
        if os.path.exists(path):
            try:
                old = load_baseline(path)
            except (OSError, ValueError):
                old = {}
            roots = [os.path.relpath(p).replace(os.sep, "/").rstrip("/")
                     for p in args.paths]

            def in_scope(key: str) -> bool:
                rel = key.split("::", 1)[0]
                # A root of "." scans the whole tree (keys are
                # cwd-relative, never "./"-prefixed).
                return any(r == "." or rel == r
                           or rel.startswith(r + "/") for r in roots)

            preserve = {k: v for k, v in old.items()
                        if not in_scope(k)}
        counts = write_baseline(findings, path, preserve=preserve)
        print(f"wrote {sum(counts.values())} baselined finding(s) "
              f"across {len(counts)} file/code key(s) to {path}"
              + (f" (preserved {len(preserve)} out-of-scope key(s))"
                 if preserve else ""))
        return 0

    baselined = 0
    if baseline_path and not args.no_baseline:
        try:
            baseline = load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            print(f"error: cannot read baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        if args.strict_reasons:
            with open(baseline_path, encoding="utf-8") as fh:
                reasons = json.load(fh).get("reasons", {})
            baseline = {k: v for k, v in baseline.items()
                        if k in reasons}
        total = len(findings)
        findings = apply_baseline(findings, baseline)
        baselined = total - len(findings)

    if args.format == "json":
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    elif args.format == "sarif":
        print(json.dumps(_to_sarif(findings), indent=2))
    else:
        for f in findings:
            print(f.format())
        n_err = sum(1 for f in findings if f.severity == "error")
        n_warn = len(findings) - n_err
        tail = (f" ({baselined} baselined finding(s) suppressed)"
                if baselined else "")
        if findings:
            print(f"{n_err} error(s), {n_warn} warning(s){tail}")
        else:
            print(f"clean: no new findings{tail}")
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # e.g. piped into head/a pager that exits
        sys.exit(0)
