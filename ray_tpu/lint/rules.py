"""The RTL rule set: distributed anti-patterns over ray_tpu's API.

Each rule is a small AST pass over one ModuleContext.  The rules target
the surface users actually write against — `@ray_tpu.remote`,
`.remote()`, `ray_tpu.get/wait/put`, actor handles — under any import
alias the module declares.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu.lint import Finding, ModuleContext, Rule, register_rule
from ray_tpu.util.check_serialize import KNOWN_UNSERIALIZABLE_CONSTRUCTORS

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_COMPS = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _loop_ancestors(ctx: ModuleContext, node: ast.AST) -> List[ast.AST]:
    """Loop/comprehension nodes containing `node`, innermost first."""
    out = []
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, _LOOPS + _COMPS):
            out.append(cur)
        cur = ctx.parents.get(cur)
    return out


def _contains_remote_call(ctx: ModuleContext, node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and ctx.is_remote_call(n)
               for n in ast.walk(node))


@register_rule
class GetInLoop(Rule):
    code = "RTL001"
    name = "get-in-loop"
    severity = "warning"
    description = ("get() inside a loop on refs produced in that loop "
                   "serializes the fetches; collect the refs and issue "
                   "one get([...]) instead")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.api_call_name(node) == "get"):
                continue
            loops = _loop_ancestors(ctx, node)
            if not loops or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, (ast.List, ast.Tuple) + _COMPS):
                # Batched get([...]): the aggregation is the fix, even
                # when the surrounding code loops for other reasons.
                continue
            if isinstance(arg, ast.Call) and \
                    _contains_remote_call(ctx, arg):
                yield self.finding(
                    ctx, node,
                    "get() of a .remote() call inside a loop fetches "
                    "results one at a time; submit all tasks first, "
                    "then get() the list of refs")
                continue
            if isinstance(arg, ast.Name):
                loop = loops[0]
                for sub in ast.walk(loop):
                    if isinstance(sub, ast.Assign) and \
                            isinstance(sub.value, ast.Call) and \
                            _contains_remote_call(ctx, sub.value) and \
                            any(isinstance(t, ast.Name)
                                and t.id == arg.id
                                for t in sub.targets):
                        yield self.finding(
                            ctx, node,
                            f"get({arg.id}) fetches a ref produced in "
                            "the same loop iteration; submit all tasks "
                            "first, then get() the list of refs")
                        break


def _options_chain_kwargs(call: ast.Call) -> Dict[str, ast.AST]:
    """kwargs of every .options(...) call in `x.options(...).remote()`
    style chains, merged."""
    out: Dict[str, ast.AST] = {}
    cur: ast.AST = call.func
    while isinstance(cur, ast.Attribute):
        cur = cur.value
        if isinstance(cur, ast.Call) and \
                isinstance(cur.func, ast.Attribute) and \
                cur.func.attr == "options":
            for kw in cur.keywords:
                if kw.arg is not None:
                    out.setdefault(kw.arg, kw.value)
            cur = cur.func
    return out


def _remote_call_base_name(call: ast.Call) -> Optional[str]:
    """The root Name a `.remote()` chain dispatches on: 'f' for
    f.remote() and A.options(...).remote(), None for deeper chains
    (handle.method.remote(), obj.attr.remote())."""
    cur: ast.AST = call.func
    if not (isinstance(cur, ast.Attribute) and cur.attr == "remote"):
        return None
    cur = cur.value
    while isinstance(cur, ast.Call) and \
            isinstance(cur.func, ast.Attribute) and \
            cur.func.attr == "options":
        cur = cur.func.value
    return cur.id if isinstance(cur, ast.Name) else None


@register_rule
class DiscardedRemoteResult(Rule):
    code = "RTL002"
    name = "discarded-remote-result"
    severity = "error"
    description = ("a .remote() call's ObjectRef is discarded: task "
                   "errors are silently lost and the result may be "
                   "GC'd before it runs")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and ctx.is_remote_call(node.value)):
                continue
            # Exemption opts come from the .options() chain AND the
            # target's own @remote(...) decorator kwargs (options win).
            opts = _options_chain_kwargs(node.value)
            base = _remote_call_base_name(node.value)
            if base is not None:
                dec = (ctx.remote_functions.get(base)
                       or ctx.remote_classes.get(base))
                if dec is not None:
                    for k, v in dec[1].items():
                        opts.setdefault(k, v)
            lifetime = opts.get("lifetime")
            if isinstance(lifetime, ast.Constant) and \
                    lifetime.value == "detached":
                # Detached actors are re-fetched via get_actor(); the
                # dropped handle is the documented pattern.
                continue
            nr = opts.get("num_returns")
            if isinstance(nr, ast.Constant) and nr.value == 0:
                # num_returns=0 is EXPLICIT fire-and-forget: there is
                # no ObjectRef to lose.
                continue
            yield self.finding(
                ctx, node,
                ".remote() result discarded — the ObjectRef is the "
                "only way to observe the task's error or output; "
                "assign it (and eventually get()/wait() it)")


_ARRAY_CTORS = {"zeros", "ones", "empty", "full", "arange", "linspace",
                "eye", "array", "asarray", "rand", "randn", "random",
                "uniform", "normal", "standard_normal"}
# Below this many elements a closure capture is cheap enough to ignore.
_LARGE_ELEMS = 16384


def _literal_elems(call: ast.Call) -> Optional[int]:
    """Element-count estimate from literal shape args; None=unknown."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else None
    dims: List[int] = []

    def shape_of(node) -> Optional[List[int]]:
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, int):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant)
                and isinstance(e.value, int) for e in node.elts):
            return [e.value for e in node.elts]
        return None

    if name in ("zeros", "ones", "empty", "full", "rand", "randn",
                "standard_normal"):
        if name in ("rand", "randn"):
            for a in call.args:
                s = shape_of(a)
                if s is None:
                    return None
                dims.extend(s)
        elif call.args:
            s = shape_of(call.args[0])
            if s is None:
                return None
            dims = s
    elif name == "arange" and call.args:
        s = shape_of(call.args[-1] if len(call.args) < 3
                     else call.args[1])
        if s is None:
            return None
        dims = s
    elif name == "linspace" and len(call.args) >= 3:
        s = shape_of(call.args[2])
        if s is None:
            return None
        dims = s
    elif name in ("array", "asarray") and call.args:
        if isinstance(call.args[0], (ast.List, ast.Tuple)):
            dims = [len(call.args[0].elts)]
        else:
            return None
    else:
        return None
    n = 1
    for d in dims:
        n *= max(1, d)
    return n


@register_rule
class ModuleArrayCapture(Rule):
    code = "RTL003"
    name = "module-array-closure-capture"
    severity = "warning"
    description = ("a large module-level np/jnp array referenced inside "
                   "a remote function is pickled into EVERY task "
                   "submission; put() it once and pass the ref")

    def _module_arrays(self, ctx: ModuleContext) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for stmt in ctx.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)):
                continue
            call = stmt.value
            fn = call.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _ARRAY_CTORS):
                continue
            root = fn
            while isinstance(root, ast.Attribute):
                root = root.value
            if not (isinstance(root, ast.Name) and
                    root.id in (ctx.np_aliases | ctx.jax_aliases)):
                continue
            n = _literal_elems(call)
            if n is not None and n < _LARGE_ELEMS:
                continue  # provably small: capture is harmless
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = stmt
        return out

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        arrays = self._module_arrays(ctx)
        if not arrays:
            return
        for _, (node, _opts) in list(ctx.remote_functions.items()) + \
                list(ctx.remote_classes.items()):
            if not isinstance(node, _DEFS + (ast.ClassDef,)):
                continue
            bound = _locally_bound_names(node)
            reported = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in arrays and sub.id not in bound and \
                        sub.id not in reported:
                    reported.add(sub.id)
                    yield self.finding(
                        ctx, sub,
                        f"module-level array {sub.id!r} is captured by "
                        "this remote closure and reserialized on every "
                        "submission; store it once with ray_tpu.put() "
                        "and pass the ObjectRef as an argument")


def _locally_bound_names(def_node: ast.AST) -> set:
    """Names bound anywhere inside `def_node` (params, assignments,
    imports, loop targets, nested defs): loads of these are NOT free
    captures."""
    bound = set()
    for sub in ast.walk(def_node):
        if isinstance(sub, _DEFS):
            a = sub.args
            for p in (a.args + a.posonlyargs + a.kwonlyargs
                      + ([a.vararg] if a.vararg else [])
                      + ([a.kwarg] if a.kwarg else [])):
                bound.add(p.arg)
            bound.add(sub.name)
        elif isinstance(sub, ast.Name) and \
                isinstance(sub.ctx, (ast.Store, ast.Del)):
            bound.add(sub.id)
        elif isinstance(sub, ast.ClassDef):
            bound.add(sub.name)
        elif isinstance(sub, (ast.Import, ast.ImportFrom)):
            for alias in sub.names:
                bound.add(alias.asname or alias.name.split(".")[0])
    return bound


@register_rule
class BlockingGetInTask(Rule):
    code = "RTL004"
    name = "blocking-get-in-task"
    severity = "error"
    description = ("get()/wait() inside a remote function or actor "
                   "method blocks a worker slot while it waits on other "
                   "tasks — with a fixed-size pool this deadlocks")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            api = ctx.api_call_name(node)
            if api not in ("get", "wait"):
                continue
            if ctx.in_remote_context(node):
                yield self.finding(
                    ctx, node,
                    f"blocking {api}() inside a remote function/actor "
                    "method holds its worker slot while waiting on "
                    "other tasks (nested-get deadlock with a bounded "
                    "pool); pass the refs as task args so the runtime "
                    "resolves them, or restructure onto the driver")


@register_rule
class ActorMethodWithoutRemote(Rule):
    code = "RTL005"
    name = "actor-call-missing-remote"
    severity = "error"
    description = ("calling handle.method(...) invokes nothing — actor "
                   "methods are only dispatched via "
                   "handle.method.remote(...)")

    def _handle_names(self, ctx: ModuleContext) -> set:
        handles = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            is_handle = False
            if ctx.api_call_name(call) == "get_actor":
                is_handle = True
            elif isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "remote":
                base = call.func.value
                # Cls.remote() or Cls.options(...).remote()
                if isinstance(base, ast.Call) and \
                        isinstance(base.func, ast.Attribute) and \
                        base.func.attr == "options":
                    base = base.func.value
                if isinstance(base, ast.Name) and \
                        base.id in ctx.remote_classes:
                    is_handle = True
            if is_handle:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        handles.add(tgt.id)
        return handles

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        handles = self._handle_names(ctx)
        if not handles:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in handles):
                continue
            if node.func.attr in ("remote", "options"):
                continue
            if node.func.attr.startswith("_"):
                # Handle __getattr__ rejects private names, so a direct
                # private call is framework-internal plumbing on a real
                # handle object, not a missed dispatch.
                continue
            yield self.finding(
                ctx, node,
                f"{node.func.value.id}.{node.func.attr}(...) calls an "
                "actor method without .remote() — nothing is "
                f"dispatched; use {node.func.value.id}."
                f"{node.func.attr}.remote(...)")


@register_rule
class UnserializableCapture(Rule):
    code = "RTL006"
    name = "unserializable-capture"
    severity = "error"
    description = ("a remote closure captures a value (lock, file "
                   "handle, generator, ...) that can never survive "
                   "serialization to a worker")

    def _unserializable_bindings(
            self, ctx: ModuleContext) -> Dict[str, Tuple[ast.AST, str]]:
        # Local aliases for the modules named in the shared table.
        table_modules = {m for m, _ in KNOWN_UNSERIALIZABLE_CONSTRUCTORS
                         if m}
        mod_alias: Dict[str, str] = {}
        from_alias: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    if root in table_modules:
                        mod_alias[alias.asname or root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                root = node.module.split(".")[0]
                if root in table_modules:
                    for alias in node.names:
                        from_alias[alias.asname or alias.name] = \
                            (root, alias.name)

        out: Dict[str, Tuple[ast.AST, str]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            reason = None
            if isinstance(val, ast.GeneratorExp):
                reason = "generators capture a paused stack frame"
            elif isinstance(val, ast.Call):
                fn = val.func
                if isinstance(fn, ast.Name):
                    key = from_alias.get(fn.id)
                    if key is None and fn.id == "open":
                        key = (None, "open")
                    if key is not None:
                        reason = KNOWN_UNSERIALIZABLE_CONSTRUCTORS.get(
                            key)
                elif isinstance(fn, ast.Attribute) and \
                        isinstance(fn.value, ast.Name):
                    mod = mod_alias.get(fn.value.id)
                    if mod is not None:
                        reason = KNOWN_UNSERIALIZABLE_CONSTRUCTORS.get(
                            (mod, fn.attr))
            if reason is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = (node, reason)
        return out

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        bindings = self._unserializable_bindings(ctx)
        if not bindings:
            return
        for _, (node, _opts) in list(ctx.remote_functions.items()) + \
                list(ctx.remote_classes.items()):
            if not isinstance(node, _DEFS + (ast.ClassDef,)):
                continue
            bound = _locally_bound_names(node)
            reported = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in bindings and sub.id not in bound and \
                        sub.id not in reported:
                    reported.add(sub.id)
                    _, reason = bindings[sub.id]
                    yield self.finding(
                        ctx, sub,
                        f"remote closure captures {sub.id!r}, which "
                        f"cannot be serialized to a worker ({reason}); "
                        "create it inside the task, or pass "
                        "serializable state instead")


def _requests_tpu(opts: Dict[str, ast.AST]) -> bool:
    for key in ("num_tpus", "num_gpus"):
        val = opts.get(key)
        if val is not None and not (isinstance(val, ast.Constant)
                                    and not val.value):
            return True
    res = opts.get("resources")
    if isinstance(res, ast.Dict):
        for k in res.keys:
            if isinstance(k, ast.Constant) and k.value == "TPU":
                return True
    elif res is not None:
        return True  # non-literal resources: assume the caller knows
    return False


@register_rule
class JaxWithoutTpuResources(Rule):
    code = "RTL007"
    name = "jax-task-without-tpu"
    severity = "warning"
    description = ("a remote function running jax/jnp compute but "
                   "requesting no TPU lands on CPU workers and "
                   "silently bypasses the accelerator")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.jax_aliases:
            return
        for name, (node, opts) in \
                list(ctx.remote_functions.items()) + \
                list(ctx.remote_classes.items()):
            if not isinstance(node, _DEFS + (ast.ClassDef,)):
                continue
            if _requests_tpu(opts):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        ctx.jax_rooted(sub.func):
                    yield self.finding(
                        ctx, node,
                        f"remote {'class' if isinstance(node, ast.ClassDef) else 'function'} "  # noqa: E501
                        f"{name!r} calls jax/jnp ops but its decorator "
                        "requests no TPU (num_tpus=... or "
                        'resources={"TPU": ...}); it will run the '
                        "compute on CPU workers")
                    break


@register_rule
class WaitMisuse(Rule):
    code = "RTL008"
    name = "wait-misuse"
    severity = "error"
    description = ("wait() returns (ready, pending); unpacking it any "
                   "other way, get()ing it directly, or polling with "
                   "timeout=0 in a loop is a bug")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx.api_call_name(node) == "wait"):
                continue
            parent = ctx.parents.get(node)
            if isinstance(parent, ast.Assign) and \
                    len(parent.targets) == 1 and \
                    isinstance(parent.targets[0],
                               (ast.Tuple, ast.List)) and \
                    len(parent.targets[0].elts) != 2:
                yield self.finding(
                    ctx, node,
                    "wait() returns exactly (ready_refs, pending_refs) "
                    f"— unpacking into {len(parent.targets[0].elts)} "
                    "targets will not do what you want")
            if isinstance(parent, ast.Call) and \
                    ctx.api_call_name(parent) == "get":
                yield self.finding(
                    ctx, node,
                    "get(wait(...)) fetches the (ready, pending) TUPLE, "
                    "not the ready values; unpack first and get() the "
                    "ready list")
            if isinstance(parent, (ast.For, ast.AsyncFor)) and \
                    parent.iter is node:
                yield self.finding(
                    ctx, node,
                    "iterating wait() yields the two lists (ready, "
                    "pending), not individual refs; unpack it")
            for kw in node.keywords:
                if kw.arg == "timeout" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value == 0 and \
                        any(isinstance(a, _LOOPS)
                            for a in _loop_ancestors(ctx, node)):
                    yield self.finding(
                        ctx, node,
                        "wait(timeout=0) in a loop busy-spins the "
                        "driver; use a positive timeout (or None) and "
                        "let wait() block")
