"""Trainable: the unit of execution Tune schedules.

Reference: python/ray/tune/trainable/trainable.py:64 (class API with
step/save/restore) and trainable/function_trainable.py:315 (function API
bridged through a report queue).  Here the function API runs the user
callable in a thread whose `session.report` calls hand results to the
driving actor one step at a time (backpressured, lossless).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air import session as air_session

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class API: subclass with setup/step/save_checkpoint/load_checkpoint."""

    def __init__(self, config: Optional[Dict] = None, trial_id: str = "",
                 trial_name: str = "", trial_dir: str = ""):
        self.config = config or {}
        self.trial_id = trial_id
        self.trial_name = trial_name
        self.trial_dir = trial_dir
        self._iteration = 0
        self._start = time.time()
        self.setup(self.config)

    # -- user hooks ---------------------------------------------------
    def setup(self, config: Dict) -> None:
        pass

    def step(self) -> Dict:
        raise NotImplementedError

    def save_checkpoint(self) -> Optional[Dict]:
        return None

    def load_checkpoint(self, checkpoint: Optional[Dict]) -> None:
        pass

    def reset_config(self, new_config: Dict) -> bool:
        return False

    def cleanup(self) -> None:
        pass

    # -- harness API --------------------------------------------------
    def train(self) -> Dict:
        result = self.step()
        if not result.pop("_rt_sentinel", False):
            self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault("trial_id", self.trial_id)
        result.setdefault("time_total_s", time.time() - self._start)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Checkpoint:
        data = self.save_checkpoint() or {}
        data["_iteration"] = self._iteration
        return Checkpoint.from_dict(data)

    def restore(self, checkpoint: Checkpoint) -> None:
        data = checkpoint.to_dict()
        self._iteration = data.pop("_iteration", 0)
        self.load_checkpoint(data)

    def reset(self, new_config: Dict) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
        return ok

    def stop(self) -> None:
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps `def train_fn(config)` using session.report for results."""

    _fn: Callable = None  # set by wrap_function subclassing

    def setup(self, config: Dict) -> None:
        self._session: Optional[air_session._Session] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._fn_done = False
        self._restore_checkpoint: Optional[Checkpoint] = None

    def _runner(self):
        air_session._set_session(self._session)
        try:
            self._fn(self.config)
        except StopIteration:
            pass
        except BaseException as e:  # surfaced by train()
            self._error = e
            traceback.print_exc()
        finally:
            self._fn_done = True
            self._session.result_queue.put(None)  # sentinel

    def _ensure_started(self):
        if self._thread is None:
            self._session = air_session._Session(
                trial_name=self.trial_name, trial_id=self.trial_id,
                trial_dir=self.trial_dir,
                checkpoint=self._restore_checkpoint)
            self._thread = threading.Thread(target=self._runner, daemon=True)
            self._thread.start()

    def step(self) -> Dict:
        self._ensure_started()
        if self._fn_done and self._session.result_queue.empty():
            # The fn already finished and its sentinel was consumed by an
            # earlier step(); blocking on the queue would hang forever.
            if self._error is not None:
                raise self._error
            return {DONE: True, "_rt_sentinel": True}
        item = self._session.result_queue.get()
        if item is None:
            if self._error is not None:
                raise self._error
            return {DONE: True, "_rt_sentinel": True}
        metrics, checkpoint = item
        if checkpoint is not None:
            self._latest_checkpoint = checkpoint
        self._session.continue_event.set()
        metrics.setdefault(DONE, False)
        return metrics

    _latest_checkpoint: Optional[Checkpoint] = None

    def save_checkpoint(self) -> Optional[Dict]:
        if self._latest_checkpoint is not None:
            return {"_fn_ckpt": self._latest_checkpoint.to_dict()}
        return None

    def load_checkpoint(self, data: Optional[Dict]) -> None:
        if data and "_fn_ckpt" in data:
            self._restore_checkpoint = Checkpoint.from_dict(data["_fn_ckpt"])

    def reset_config(self, new_config: Dict) -> bool:
        # Tear the thread down; next step() restarts the fn fresh with the
        # restored checkpoint (PBT exploit path).
        if self._session is not None:
            self._session.stop_requested = True
            self._session.continue_event.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._thread = None
        self._session = None
        self._fn_done = False
        self._error = None
        return True

    def cleanup(self) -> None:
        self.reset_config(self.config)


def wrap_function(train_fn: Callable) -> type:
    """Build a FunctionTrainable subclass bound to `train_fn` (reference:
    function_trainable.py:595 wrap_function)."""

    class _Wrapped(FunctionTrainable):
        _fn = staticmethod(train_fn)

    _Wrapped.__name__ = getattr(train_fn, "__name__", "fn") + "_trainable"
    return _Wrapped


def with_parameters(trainable: Callable, **kwargs):
    """Bind large objects to a trainable WITHOUT baking them into the
    pickled function (reference: tune/trainable/util.py
    with_parameters): each value is put() into the object store once,
    and every trial fetches it zero-copy instead of re-shipping it in
    the trial spec.

    >>> data = load_big_dataset()
    >>> tuner = Tuner(with_parameters(train_fn, data=data), ...)
    ... def train_fn(config, data): ...
    """
    import functools

    import ray_tpu

    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        raise TypeError(
            "with_parameters supports function trainables; for a class "
            "Trainable, put() the objects yourself and pass the refs "
            "through config (a wrapped class would hide the "
            "Trainable lifecycle the runner drives)")
    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    @functools.wraps(trainable)
    def _inner(config):
        resolved = {k: ray_tpu.get(r, timeout=600)
                    for k, r in refs.items()}
        return trainable(config, **resolved)

    if hasattr(trainable, "__name__"):
        _inner.__name__ = trainable.__name__ + "_with_parameters"
    return _inner
