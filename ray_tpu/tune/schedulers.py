"""Trial schedulers: early stopping + population-based training.

Reference: python/ray/tune/schedulers — ASHA (async_hyperband.py), PBT
(pbt.py), median stopping (median_stopping_rule.py), FIFO (trial_scheduler
.py).  Decision protocol mirrors the reference's TrialScheduler:
on_trial_result -> CONTINUE | STOP | PAUSE-equivalent actions.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict]) -> None:
        pass

    def choose_trial_to_run(self, trials) -> Optional[object]:
        for t in trials:
            if t.status == "PENDING":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving.  A trial reaching a rung
    continues only if its score is in the top 1/reduction_factor of
    results recorded at that rung so far."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self._rungs: List[tuple] = []  # (level, {trial_id: score})
        t = grace_period
        while t < max_t:
            self._rungs.append((t, {}))
            t *= reduction_factor
        self._rungs.sort(reverse=True)

    def _score(self, result):
        s = result.get(self.metric)
        if s is None:
            return None
        return s if self.mode == "max" else -s

    def on_trial_result(self, trial, result) -> str:
        t = result.get("training_iteration", 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        for level, recorded in self._rungs:
            if t < level:
                continue
            # Record at first arrival, then keep re-evaluating on later
            # results: under lockstep execution a bad trial can be first to
            # every rung (cutoff == itself), so a record-time-only check
            # never stops it.  The record tracks the trial's running best
            # at/after the rung, and the trial is judged on that record —
            # never on a dipping live score — so the rung leader can't be
            # stopped by its own cutoff, while trials strictly outside the
            # top 1/rf of the rung's records are stopped as soon as enough
            # peers record (successive-halving rule, applied continuously).
            prev = recorded.get(trial.trial_id)
            recorded[trial.trial_id] = score if prev is None \
                else max(prev, score)
            vals = sorted(recorded.values(), reverse=True)
            k = max(1, math.ceil(len(vals) / self.rf))
            cutoff = vals[k - 1]
            if recorded[trial.trial_id] < cutoff:
                return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same iteration (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def _score(self, result):
        s = result.get(self.metric)
        return None if s is None else (s if self.mode == "max" else -s)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is None:
            return CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(score)
        t = result.get("training_iteration", len(hist))
        if t < self.grace:
            return CONTINUE
        other_avgs = [sum(h) / len(h)
                      for tid, h in self._histories.items()
                      if tid != trial.trial_id and h]
        if len(other_avgs) < self.min_samples:
            return CONTINUE
        median = sorted(other_avgs)[len(other_avgs) // 2]
        if max(hist) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: at each perturbation interval, bottom-quantile trials clone the
    state of top-quantile trials and perturb their hyperparams (reference:
    schedulers/pbt.py).  The runner performs the actual exploit via the
    (checkpoint, new_config) we return through `pbt_exploit`."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}
        self._rng = random.Random(seed)
        self.pending_exploits: Dict[str, str] = {}  # victim -> donor

    def _score(self, result):
        s = result.get(self.metric)
        return None if s is None else (s if self.mode == "max" else -s)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is not None:
            self._latest[trial.trial_id] = score
        t = result.get("training_iteration", 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._latest) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._latest, key=self._latest.get)
        k = max(1, int(len(ranked) * self.quantile))
        bottom, top = ranked[:k], ranked[-k:]
        if trial.trial_id in bottom:
            donor = self._rng.choice(top)
            if donor != trial.trial_id:
                self.pending_exploits[trial.trial_id] = donor
        return CONTINUE

    def explore(self, config: Dict) -> Dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                new[key] = self._rng.choice(spec)
            elif key in new:
                new[key] = (new[key] * self._rng.choice([0.8, 1.2]))
        return new


class PB2(PopulationBasedTraining):
    """Population Based Bandits: PBT where exploit targets' new
    hyperparameters come from a GP-UCB model over (time, config) ->
    score improvement, instead of random perturbation (reference:
    tune/schedulers/pb2.py — implemented natively with a numpy RBF-kernel
    GP; no external BO dependency).

    hyperparam_bounds: {name: (low, high)} continuous ranges the bandit
    searches over."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.5, seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = dict(hyperparam_bounds or {})
        self.kappa = ucb_kappa
        self._np_rng = None
        # Observation rows: (t, normalized config vector, score delta).
        self._obs_t: list = []
        self._obs_x: list = []
        self._obs_y: list = []
        self._prev_score: Dict[str, float] = {}
        self._trial_config: Dict[str, Dict] = {}

    def _rng_np(self):
        import numpy as np
        if self._np_rng is None:
            self._np_rng = np.random.RandomState(
                self._rng.randrange(1 << 31))
        return self._np_rng

    def _norm(self, config: Dict):
        import numpy as np
        out = []
        for name, (lo, hi) in self.bounds.items():
            v = float(config.get(name, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return np.asarray(out)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is not None:
            prev = self._prev_score.get(trial.trial_id)
            self._prev_score[trial.trial_id] = score
            self._trial_config[trial.trial_id] = dict(trial.config)
            if prev is not None and self.bounds:
                self._obs_t.append(
                    float(result.get("training_iteration", 0)))
                self._obs_x.append(self._norm(trial.config))
                self._obs_y.append(score - prev)
        return super().on_trial_result(trial, result)

    def explore(self, config: Dict) -> Dict:
        """GP-UCB over the bounded hyperparams: fit an RBF-kernel GP on
        (t, x) -> score-delta observations, score a random candidate set,
        take the UCB argmax.  Cold-starts (too few observations) fall
        back to uniform sampling inside the bounds."""
        import numpy as np
        new = dict(config)
        if not self.bounds:
            return new
        rng = self._rng_np()
        n_cand = 64
        cands = rng.uniform(size=(n_cand, len(self.bounds)))
        if len(self._obs_y) >= 4:
            t = np.asarray(self._obs_t)
            t = (t - t.min()) / max(t.max() - t.min(), 1e-12)
            X = np.column_stack([t, np.vstack(self._obs_x)])
            y = np.asarray(self._obs_y)
            y_std = y.std() or 1.0
            y_n = (y - y.mean()) / y_std
            t_now = 1.0
            C = np.column_stack([np.full(n_cand, t_now), cands])
            ls = 0.3  # RBF length scale in normalized units
            noise = 1e-2

            def rbf(A, B):
                d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
                return np.exp(-d2 / (2 * ls * ls))

            K = rbf(X, X) + noise * np.eye(len(X))
            Ks = rbf(C, X)
            try:
                Kinv_y = np.linalg.solve(K, y_n)
                mu = Ks @ Kinv_y
                Kinv_Ks = np.linalg.solve(K, Ks.T)
                var = np.clip(1.0 - (Ks * Kinv_Ks.T).sum(1), 1e-9, None)
                ucb = mu + self.kappa * np.sqrt(var)
                best = cands[int(np.argmax(ucb))]
            except np.linalg.LinAlgError:
                best = cands[0]
        else:
            best = cands[0]
        for j, (name, (lo, hi)) in enumerate(self.bounds.items()):
            new[name] = float(lo + best[j] * (hi - lo))
        return new


class HyperBandScheduler(AsyncHyperBandScheduler):
    """Synchronous HyperBand approximated by its asynchronous variant (the
    reference ships both; ASHA dominates in practice)."""
