"""Trial schedulers: early stopping + population-based training.

Reference: python/ray/tune/schedulers — ASHA (async_hyperband.py), PBT
(pbt.py), median stopping (median_stopping_rule.py), FIFO (trial_scheduler
.py).  Decision protocol mirrors the reference's TrialScheduler:
on_trial_result -> CONTINUE | STOP | PAUSE-equivalent actions.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict]) -> None:
        pass

    def choose_trial_to_run(self, trials) -> Optional[object]:
        for t in trials:
            if t.status == "PENDING":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving.  A trial reaching a rung
    continues only if its score is in the top 1/reduction_factor of
    results recorded at that rung so far."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self._rungs: List[tuple] = []  # (level, {trial_id: score})
        t = grace_period
        while t < max_t:
            self._rungs.append((t, {}))
            t *= reduction_factor
        self._rungs.sort(reverse=True)

    def _score(self, result):
        s = result.get(self.metric)
        if s is None:
            return None
        return s if self.mode == "max" else -s

    def on_trial_result(self, trial, result) -> str:
        t = result.get("training_iteration", 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        for level, recorded in self._rungs:
            if t < level:
                continue
            # Record at first arrival, then keep re-evaluating on later
            # results: under lockstep execution a bad trial can be first to
            # every rung (cutoff == itself), so a record-time-only check
            # never stops it.  The record tracks the trial's running best
            # at/after the rung, and the trial is judged on that record —
            # never on a dipping live score — so the rung leader can't be
            # stopped by its own cutoff, while trials strictly outside the
            # top 1/rf of the rung's records are stopped as soon as enough
            # peers record (successive-halving rule, applied continuously).
            prev = recorded.get(trial.trial_id)
            recorded[trial.trial_id] = score if prev is None \
                else max(prev, score)
            vals = sorted(recorded.values(), reverse=True)
            k = max(1, math.ceil(len(vals) / self.rf))
            cutoff = vals[k - 1]
            if recorded[trial.trial_id] < cutoff:
                return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same iteration (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def _score(self, result):
        s = result.get(self.metric)
        return None if s is None else (s if self.mode == "max" else -s)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is None:
            return CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(score)
        t = result.get("training_iteration", len(hist))
        if t < self.grace:
            return CONTINUE
        other_avgs = [sum(h) / len(h)
                      for tid, h in self._histories.items()
                      if tid != trial.trial_id and h]
        if len(other_avgs) < self.min_samples:
            return CONTINUE
        median = sorted(other_avgs)[len(other_avgs) // 2]
        if max(hist) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: at each perturbation interval, bottom-quantile trials clone the
    state of top-quantile trials and perturb their hyperparams (reference:
    schedulers/pbt.py).  The runner performs the actual exploit via the
    (checkpoint, new_config) we return through `pbt_exploit`."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}
        self._rng = random.Random(seed)
        self.pending_exploits: Dict[str, str] = {}  # victim -> donor

    def _score(self, result):
        s = result.get(self.metric)
        return None if s is None else (s if self.mode == "max" else -s)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is not None:
            self._latest[trial.trial_id] = score
        t = result.get("training_iteration", 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._latest) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._latest, key=self._latest.get)
        k = max(1, int(len(ranked) * self.quantile))
        bottom, top = ranked[:k], ranked[-k:]
        if trial.trial_id in bottom:
            donor = self._rng.choice(top)
            if donor != trial.trial_id:
                self.pending_exploits[trial.trial_id] = donor
        return CONTINUE

    def explore(self, config: Dict) -> Dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                new[key] = self._rng.choice(spec)
            elif key in new:
                new[key] = (new[key] * self._rng.choice([0.8, 1.2]))
        return new


class HyperBandScheduler(AsyncHyperBandScheduler):
    """Synchronous HyperBand approximated by its asynchronous variant (the
    reference ships both; ASHA dominates in practice)."""
