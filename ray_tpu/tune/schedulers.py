"""Trial schedulers: early stopping + population-based training.

Reference: python/ray/tune/schedulers — ASHA (async_hyperband.py), PBT
(pbt.py), median stopping (median_stopping_rule.py), FIFO (trial_scheduler
.py).  Decision protocol mirrors the reference's TrialScheduler:
on_trial_result -> CONTINUE | STOP | PAUSE-equivalent actions.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict]) -> None:
        pass

    def choose_trial_to_run(self, trials) -> Optional[object]:
        for t in trials:
            if t.status == "PENDING":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving.  A trial reaching a rung
    continues only if its score is in the top 1/reduction_factor of
    results recorded at that rung so far."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self._rungs: List[tuple] = []  # (level, {trial_id: score})
        t = grace_period
        while t < max_t:
            self._rungs.append((t, {}))
            t *= reduction_factor
        self._rungs.sort(reverse=True)

    def _score(self, result):
        s = result.get(self.metric)
        if s is None:
            return None
        return s if self.mode == "max" else -s

    def on_trial_result(self, trial, result) -> str:
        t = result.get("training_iteration", 0)
        if t >= self.max_t:
            return STOP
        score = self._score(result)
        if score is None:
            return CONTINUE
        for level, recorded in self._rungs:
            if t < level:
                continue
            # Record at first arrival, then keep re-evaluating on later
            # results: under lockstep execution a bad trial can be first to
            # every rung (cutoff == itself), so a record-time-only check
            # never stops it.  The record tracks the trial's running best
            # at/after the rung, and the trial is judged on that record —
            # never on a dipping live score — so the rung leader can't be
            # stopped by its own cutoff, while trials strictly outside the
            # top 1/rf of the rung's records are stopped as soon as enough
            # peers record (successive-halving rule, applied continuously).
            prev = recorded.get(trial.trial_id)
            recorded[trial.trial_id] = score if prev is None \
                else max(prev, score)
            vals = sorted(recorded.values(), reverse=True)
            k = max(1, math.ceil(len(vals) / self.rf))
            cutoff = vals[k - 1]
            if recorded[trial.trial_id] < cutoff:
                return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same iteration (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._histories: Dict[str, List[float]] = {}

    def _score(self, result):
        s = result.get(self.metric)
        return None if s is None else (s if self.mode == "max" else -s)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is None:
            return CONTINUE
        hist = self._histories.setdefault(trial.trial_id, [])
        hist.append(score)
        t = result.get("training_iteration", len(hist))
        if t < self.grace:
            return CONTINUE
        other_avgs = [sum(h) / len(h)
                      for tid, h in self._histories.items()
                      if tid != trial.trial_id and h]
        if len(other_avgs) < self.min_samples:
            return CONTINUE
        median = sorted(other_avgs)[len(other_avgs) // 2]
        if max(hist) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: at each perturbation interval, bottom-quantile trials clone the
    state of top-quantile trials and perturb their hyperparams (reference:
    schedulers/pbt.py).  The runner performs the actual exploit via the
    (checkpoint, new_config) we return through `pbt_exploit`."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25, seed: Optional[int] = None):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}
        self._rng = random.Random(seed)
        self.pending_exploits: Dict[str, str] = {}  # victim -> donor

    def _score(self, result):
        s = result.get(self.metric)
        return None if s is None else (s if self.mode == "max" else -s)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is not None:
            self._latest[trial.trial_id] = score
        t = result.get("training_iteration", 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._latest) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._latest, key=self._latest.get)
        k = max(1, int(len(ranked) * self.quantile))
        bottom, top = ranked[:k], ranked[-k:]
        if trial.trial_id in bottom:
            donor = self._rng.choice(top)
            if donor != trial.trial_id:
                self.pending_exploits[trial.trial_id] = donor
        return CONTINUE

    def explore(self, config: Dict) -> Dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                new[key] = self._rng.choice(spec)
            elif key in new:
                new[key] = (new[key] * self._rng.choice([0.8, 1.2]))
        return new


class PB2(PopulationBasedTraining):
    """Population Based Bandits: PBT where exploit targets' new
    hyperparameters come from a GP-UCB model over (time, config) ->
    score improvement, instead of random perturbation (reference:
    tune/schedulers/pb2.py — implemented natively with a numpy RBF-kernel
    GP; no external BO dependency).

    hyperparam_bounds: {name: (low, high)} continuous ranges the bandit
    searches over."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.5, seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = dict(hyperparam_bounds or {})
        self.kappa = ucb_kappa
        self._np_rng = None
        # Observation rows: (t, normalized config vector, score delta).
        self._obs_t: list = []
        self._obs_x: list = []
        self._obs_y: list = []
        self._prev_score: Dict[str, float] = {}
        self._trial_config: Dict[str, Dict] = {}

    def _rng_np(self):
        import numpy as np
        if self._np_rng is None:
            self._np_rng = np.random.RandomState(
                self._rng.randrange(1 << 31))
        return self._np_rng

    def _norm(self, config: Dict):
        import numpy as np
        out = []
        for name, (lo, hi) in self.bounds.items():
            v = float(config.get(name, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return np.asarray(out)

    def on_trial_result(self, trial, result) -> str:
        score = self._score(result)
        if score is not None:
            prev = self._prev_score.get(trial.trial_id)
            self._prev_score[trial.trial_id] = score
            self._trial_config[trial.trial_id] = dict(trial.config)
            if prev is not None and self.bounds:
                self._obs_t.append(
                    float(result.get("training_iteration", 0)))
                self._obs_x.append(self._norm(trial.config))
                self._obs_y.append(score - prev)
        return super().on_trial_result(trial, result)

    def explore(self, config: Dict) -> Dict:
        """GP-UCB over the bounded hyperparams: fit an RBF-kernel GP on
        (t, x) -> score-delta observations, score a random candidate set,
        take the UCB argmax.  Cold-starts (too few observations) fall
        back to uniform sampling inside the bounds."""
        import numpy as np
        new = dict(config)
        if not self.bounds:
            return new
        rng = self._rng_np()
        n_cand = 64
        cands = rng.uniform(size=(n_cand, len(self.bounds)))
        if len(self._obs_y) >= 4:
            t = np.asarray(self._obs_t)
            t = (t - t.min()) / max(t.max() - t.min(), 1e-12)
            X = np.column_stack([t, np.vstack(self._obs_x)])
            y = np.asarray(self._obs_y)
            y_std = y.std() or 1.0
            y_n = (y - y.mean()) / y_std
            t_now = 1.0
            C = np.column_stack([np.full(n_cand, t_now), cands])
            ls = 0.3  # RBF length scale in normalized units
            noise = 1e-2

            def rbf(A, B):
                d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
                return np.exp(-d2 / (2 * ls * ls))

            K = rbf(X, X) + noise * np.eye(len(X))
            Ks = rbf(C, X)
            try:
                Kinv_y = np.linalg.solve(K, y_n)
                mu = Ks @ Kinv_y
                Kinv_Ks = np.linalg.solve(K, Ks.T)
                var = np.clip(1.0 - (Ks * Kinv_Ks.T).sum(1), 1e-9, None)
                ucb = mu + self.kappa * np.sqrt(var)
                best = cands[int(np.argmax(ucb))]
            except np.linalg.LinAlgError:
                best = cands[0]
        else:
            best = cands[0]
        for j, (name, (lo, hi)) in enumerate(self.bounds.items()):
            new[name] = float(lo + best[j] * (hi - lo))
        return new


class HyperBandScheduler(TrialScheduler):
    """SYNCHRONOUS HyperBand (Li et al. 2018; reference:
    tune/schedulers/hyperband.py).

    Trials are grouped into brackets; each bracket runs successive-
    halving ROUNDS in lockstep: every live member trains to the
    bracket's current milestone and is then PAUSED (checkpointed, actor
    + placement group released).  When the last member arrives, the top
    1/eta by the recorded milestone score resume toward the next
    milestone (eta x longer) and the rest stop.  Unlike ASHA there is
    no first-arrival bias: promotion decisions always see the whole
    rung.

    Bracket shapes follow the paper: with s_max = floor(log_eta(max_t /
    grace)), bracket s holds n_s = ceil((s_max+1)/(s+1) * eta^s) trials
    starting at milestone r_s = max_t * eta^-s; brackets are filled in
    s descending order, cycling if more trials arrive.

    Runner protocol: `on_trial_result` returns PAUSE at milestones; the
    runner checkpoints + tears down the trial (status PAUSED) and each
    loop iteration drains `pop_actions()` -> (resume, stop) trial
    lists.
    """

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 81, grace_period: int = 1,
                 reduction_factor: int = 3):
        assert mode in ("max", "min")
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self.eta = reduction_factor
        self.grace = max(1, grace_period)
        s_max = int(math.floor(
            math.log(max(max_t / self.grace, 1), self.eta)))
        self._templates = []
        for s in range(s_max, -1, -1):
            n = int(math.ceil((s_max + 1) / (s + 1) * self.eta ** s))
            r = max(self.grace, int(round(max_t * self.eta ** (-s))))
            self._templates.append((n, r))
        self._ti = 0
        self._brackets: List[Dict] = []
        self._by_trial: Dict[str, Dict] = {}
        self._resume: List[object] = []
        self._stop: List[object] = []

    def _score(self, result):
        s = result.get(self.metric)
        if s is None:
            return None
        return s if self.mode == "max" else -s

    def on_trial_add(self, trial) -> None:
        if (not self._brackets
                or len(self._brackets[-1]["members"])
                >= self._brackets[-1]["n"]):
            n, r = self._templates[self._ti % len(self._templates)]
            self._ti += 1
            self._brackets.append({"n": n, "r": r, "members": {}})
        b = self._brackets[-1]
        b["members"][trial.trial_id] = {
            "trial": trial, "score": None, "recorded": False,
            "dead": False}
        self._by_trial[trial.trial_id] = b

    def on_trial_result(self, trial, result) -> str:
        b = self._by_trial.get(trial.trial_id)
        if b is None:
            return CONTINUE
        m = b["members"][trial.trial_id]
        t = result.get("training_iteration", 0)
        score = self._score(result)
        if score is not None:
            # Latest score, NOT a running max: synchronous HyperBand
            # compares rung members at the rung — a stale early peak
            # must not outrank a peer whose current score is better
            # (the recording result IS the at-milestone value).
            m["score"] = score
        if t >= self.max_t:
            m["dead"] = True
            self._maybe_advance(b)
            return STOP
        if t >= b["r"]:
            m["recorded"] = True
            self._maybe_advance(b, inline=m)
            # _maybe_advance may have resolved this trial immediately
            # (it was the last arrival): a winner never actually pauses
            # — it just keeps training; a loser stops without the
            # pause-then-stop dance.
            if m["dead"]:
                return STOP
            if not m["recorded"]:
                return CONTINUE
            return PAUSE
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[Dict]) -> None:
        b = self._by_trial.get(trial.trial_id)
        if b is None:
            return
        b["members"][trial.trial_id]["dead"] = True
        self._maybe_advance(b)

    def _maybe_advance(self, b: Dict, allow_partial: bool = False,
                       inline=None) -> None:
        """If every live member of the bracket has recorded the current
        milestone, promote the top 1/eta and stop the rest.  A bracket
        only rounds once fully populated (more trials may still arrive
        for it) unless the runner signals exhaustion via
        force_advance -> allow_partial.  `inline` is the member whose
        result triggered the call — if it wins it continues in place
        (never paused), so it must not enter the resume queue."""
        if len(b["members"]) < b["n"] and not allow_partial:
            return
        live = [m for m in b["members"].values() if not m["dead"]]
        if not live or not all(m["recorded"] for m in live):
            return
        ranked = sorted(live, key=lambda m: (m["score"] is not None,
                                             m["score"]), reverse=True)
        keep = max(1, len(live) // self.eta)
        next_r = min(b["r"] * self.eta, self.max_t)
        if next_r <= b["r"]:
            # Final rung already at max_t: everyone stops.
            winners, losers = [], ranked
        else:
            winners, losers = ranked[:keep], ranked[keep:]
        b["r"] = next_r
        for m in winners:
            m["recorded"] = False
            if m is not inline:
                self._resume.append(m["trial"])
        for m in losers:
            m["dead"] = True
            self._stop.append(m["trial"])

    def pop_actions(self):
        """Drain (trials_to_resume, trials_to_stop) — runner hook."""
        resume, self._resume = self._resume, []
        stop, self._stop = self._stop, []
        return resume, stop

    def force_advance(self) -> bool:
        """Fail-open hook: the runner found only PAUSED trials and no
        pending work — treat every bracket's missing members as never
        arriving and advance on what was recorded."""
        progressed = False
        for b in self._brackets:
            live = [m for m in b["members"].values() if not m["dead"]]
            if not live:
                continue
            if all(m["recorded"] for m in live):
                # Under-full bracket (fewer samples than the template
                # shape): round on what exists.
                self._maybe_advance(b, allow_partial=True)
                progressed = True
        return progressed
