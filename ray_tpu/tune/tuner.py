"""Tuner: the experiment front door (reference: python/ray/tune/tuner.py:212
Tuner.fit -> impl/tuner_internal.py:278 -> tune.py:129 tune.run)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ray_tpu.air.config import RunConfig
from ray_tpu.air.result import Result
from ray_tpu.tune.execution.trial_runner import (
    TERMINATED, Trial, TrialRunner, best_trial)
from ray_tpu.tune.trainable import Trainable, wrap_function


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    search_alg: Any = None
    scheduler: Any = None


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self._trials = trials
        self._metric, self._mode = metric, mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        t = self._trials[i]
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error, path=t.trial_dir, config=t.config)

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    @property
    def errors(self):
        return [t.error for t in self._trials if t.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            done = [t for t in self._trials if t.status == TERMINATED]
            t = done[0] if done else self._trials[0]
        else:
            t = best_trial(self._trials, metric, mode)
            if t is None:
                raise ValueError(f"no trial reported metric {metric!r}")
        return Result(metrics=t.last_result, checkpoint=t.checkpoint,
                      error=t.error, path=t.trial_dir, config=t.config)

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([{**t.last_result,
                              **{f"config/{k}": v
                                 for k, v in t.config.items()
                                 if not isinstance(v, dict)}}
                             for t in self._trials])


class Tuner:
    def __init__(self, trainable, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._param_space = param_space or {}
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self._trainable_cls = trainable
            self._name = trainable.__name__
        elif callable(trainable):
            self._trainable_cls = wrap_function(trainable)
            self._name = getattr(trainable, "__name__", "fn")
        else:
            raise ValueError(f"cannot tune {trainable!r}")
        self._pg_factory = getattr(trainable, "_pg_factory", None)

    _restore_path: Optional[str] = None

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        runner = TrialRunner(
            self._trainable_cls,
            param_space=self._param_space,
            search_alg=tc.search_alg,
            scheduler=tc.scheduler,
            num_samples=tc.num_samples,
            max_concurrent=tc.max_concurrent_trials,
            metric=tc.metric, mode=tc.mode,
            run_config=self._run_config,
            pg_factory=self._pg_factory,
            trainable_name=self._name)
        if self._restore_path:
            runner.experiment_dir = self._restore_path
            if not runner.restore_experiment_state():
                raise FileNotFoundError(
                    f"no experiment state under {self._restore_path!r}")
        trials = runner.run()
        return ResultGrid(trials, tc.metric, tc.mode)

    @classmethod
    def restore(cls, path: str, trainable, *,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory
        (reference: Tuner.restore tuner.py): finished trials keep their
        results, unfinished ones restart from their last checkpoint."""
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        tuner._restore_path = path
        return tuner


def with_resources(trainable, resources) -> Any:
    """Attach trial resources (reference: tune/trainable/util.py
    with_resources): dict {"CPU": n} or a PlacementGroupFactory.

    Returns a WRAPPED trainable — the caller's object is never
    mutated, so an earlier with_resources cannot leak its placement
    factory into later unrelated runs of the same function/class."""
    import functools

    from ray_tpu.tune.execution.placement_groups import (
        PlacementGroupFactory, resource_dict_to_pg_factory)
    if isinstance(resources, PlacementGroupFactory):
        pgf = resources
    else:
        pgf = resource_dict_to_pg_factory(resources)
    if isinstance(trainable, type):
        wrapped = type(trainable.__name__, (trainable,), {})
    else:
        @functools.wraps(trainable)
        def wrapped(*a, **kw):
            return trainable(*a, **kw)
    wrapped._pg_factory = pgf
    return wrapped
