"""Console progress reporting for tune runs.

Reference: python/ray/tune/progress_reporter.py (CLIReporter — a
throttled trial-status table printed on results and at experiment
end; metric columns picked explicitly or auto-detected).

Implemented as a ``tune.logger.Callback`` so it rides the same
dispatch as every other logger; ``RunConfig(verbose=2)`` appends one
automatically unless the callbacks already include a CLIReporter
(a custom non-CLIReporter progress callback does NOT suppress the
auto-install — pass verbose<=1 to silence the built-in table).
"""

from __future__ import annotations

import numbers
import time
from typing import Dict, List, Optional

from ray_tpu.tune.logger import Callback, _flatten

_STATUS_ORDER = ("RUNNING", "PENDING", "PAUSED", "TERMINATED", "ERROR")
_AUTO_METRIC_CAP = 4
_SKIP_AUTO = {"training_iteration", "done", "timestamp",
              "time_total_s", "trial_id"}


class CLIReporter(Callback):
    """Throttled trial-status table (reference: CLIReporter —
    ``max_report_frequency`` seconds between tables, plus a final
    table at experiment end)."""

    def __init__(self, metric_columns: Optional[List[str]] = None,
                 max_report_frequency: float = 5.0):
        self._metric_columns = list(metric_columns or [])
        self._freq = max_report_frequency
        self._last = 0.0
        self._runner = None

    def setup(self, runner) -> None:
        self._runner = runner

    def on_trial_result(self, trial, result: Dict) -> None:
        if not self._metric_columns:
            # Auto-detect: first few numeric keys the experiment
            # reports (reference auto-populates the same way).
            for k, v in _flatten(result).items():
                if (k not in _SKIP_AUTO
                        and isinstance(v, numbers.Number)
                        and not isinstance(v, bool)):
                    self._metric_columns.append(k)
                    if len(self._metric_columns) >= _AUTO_METRIC_CAP:
                        break
        now = time.monotonic()
        if now - self._last < self._freq:
            return
        self._last = now
        self._print_table()

    def on_trial_complete(self, trial) -> None:
        self._last = 0.0  # a finished trial always earns a table

    def on_trial_error(self, trial) -> None:
        self._last = 0.0  # an errored trial is equally final

    def on_experiment_end(self, trials: List) -> None:
        self._print_table(final=True)

    def _print_table(self, final: bool = False) -> None:
        trials = self._runner.trials if self._runner is not None else []
        if not trials:
            return
        counts: Dict[str, int] = {}
        for t in trials:
            counts[t.status] = counts.get(t.status, 0) + 1
        status_line = " | ".join(
            f"{s}: {counts[s]}" for s in _STATUS_ORDER if s in counts)
        cols = ["trial", "status", "iter"] + self._metric_columns
        rows = [cols]
        for t in trials:
            flat = _flatten(t.last_result or {})
            rows.append(
                [t.name, t.status,
                 str(flat.get("training_iteration", ""))]
                + [_fmt(flat.get(m)) for m in self._metric_columns])
        widths = [max(len(r[i]) for r in rows)
                  for i in range(len(cols))]
        sep = "+".join("-" * (w + 2) for w in widths)
        lines = [("== trial progress (final) =="
                  if final else "== trial progress =="),
                 status_line, sep]
        for r in rows:
            lines.append(" | ".join(v.ljust(w)
                                    for v, w in zip(r, widths)))
        lines.append(sep)
        print("\n".join(lines))


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, float):
        return f"{v:.5g}"
    return str(v)
