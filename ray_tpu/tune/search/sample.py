"""Search-space primitives (reference: python/ray/tune/search/sample.py —
Domain/Categorical/Float/Integer + tune.grid_search/choice/uniform/...)."""

from __future__ import annotations

import random
from typing import Any, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            import math
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Quantized(Domain):
    def __init__(self, inner: Domain, q: float):
        self.inner, self.q = inner, q

    def sample(self, rng):
        v = self.inner.sample(rng)
        return round(v / self.q) * self.q


def grid_search(values: Sequence) -> dict:
    """Marker expanded into a cross-product by BasicVariantGenerator."""
    return {"grid_search": list(values)}


def choice(categories: Sequence) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int = 1) -> Quantized:
    return Quantized(Integer(lower, upper), q)


def quniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper), q)


def sample_from(fn) -> "Function":
    return Function(fn)


class Function(Domain):
    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)
