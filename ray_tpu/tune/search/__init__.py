from ray_tpu.tune.search.sample import (  # noqa: F401
    Categorical, Domain, Float, Function, Integer, Quantized,
    choice, grid_search, loguniform, qrandint, quniform, randint,
    sample_from, uniform,
)
from ray_tpu.tune.search.basic_variant import (  # noqa: F401
    BasicVariantGenerator, Searcher,
)
from ray_tpu.tune.search.tpe import TPESearcher  # noqa: F401
from ray_tpu.tune.search.gp import GPSearch  # noqa: F401
from ray_tpu.tune.search.adapter import (  # noqa: F401
    ConcurrencyLimiter, ExternalSearcher, OptunaSearch, Repeater,
    SkoptLikeGP,
)
from ray_tpu.tune.search.bohb import (  # noqa: F401
    BOHBSearcher, HyperBandForBOHB,
)
