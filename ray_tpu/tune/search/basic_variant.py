"""Trial-variant generation: grid cross-product x sampled domains.

Reference: python/ray/tune/search/basic_variant.py (BasicVariantGenerator)
and search/searcher.py (Searcher interface for pluggable algorithms).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search.sample import Domain


class Searcher:
    """Pluggable suggestion algorithm (reference: search/searcher.py).

    Subclass and implement suggest/on_trial_complete for BO-style
    algorithms; BasicVariantGenerator covers grid/random natively.

    suggest() contract: a config dict starts a trial; ``None`` means
    the space is exhausted (the experiment winds down); ``DEFER`` means
    "nothing right now, ask again after results land" (used by
    ConcurrencyLimiter — the reference expresses the same tri-state
    with its None vs Searcher.FINISHED sentinel)."""

    DEFER = "__defer__"

    def suggest(self, trial_id: str) -> Optional[Dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass


def _find_grid_axes(space: Dict, prefix=()) -> List[tuple]:
    axes = []
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict) and set(v.keys()) == {"grid_search"}:
            axes.append((path, v["grid_search"]))
        elif isinstance(v, dict):
            axes.extend(_find_grid_axes(v, path))
    return axes


def _set_path(cfg: Dict, path: tuple, value):
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


def _resolve(space: Any, rng: random.Random):
    if isinstance(space, Domain):
        return space.sample(rng)
    if isinstance(space, dict):
        return {k: _resolve(v, rng) for k, v in space.items()}
    return space


class BasicVariantGenerator(Searcher):
    """Expand grid_search axes into a cross-product; sample Domains for
    each of num_samples repetitions."""

    def __init__(self, param_space: Dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = param_space or {}
        self._rng = random.Random(seed)
        axes = _find_grid_axes(self._space)
        grids = [list(vals) for _, vals in axes]
        self._axes = [path for path, _ in axes]
        combos = list(itertools.product(*grids)) if grids else [()]
        self._queue: List[Dict] = []
        for _ in range(num_samples):
            for combo in combos:
                cfg = _resolve(
                    {k: v for k, v in self._space.items()}, self._rng)
                for path, val in zip(self._axes, combo):
                    _set_path(cfg, path, val)
                self._queue.append(cfg)

    @property
    def total_trials(self) -> int:
        return len(self._queue)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if not self._queue:
            return None
        return self._queue.pop(0)
