"""BOHB: Bayesian-Optimization HyperBand (Falkner et al. 2018).

The one scheduler/searcher PAIR in the reference:
python/ray/tune/schedulers/hb_bohb.py:14 (HyperBandForBOHB) +
python/ray/tune/search/bohb/bohb_search.py:50 (TuneBOHB).  HyperBand
allocates budgets through synchronous successive halving; the searcher
replaces HyperBand's random config draws with samples from a
per-budget density model, so later brackets start from configs that
already look good at the budgets seen so far.

Model (the paper's recipe, on the native TPE estimators from tpe.py):
keep (config, score) observations keyed by the BUDGET they were
measured at (training_iteration at the recording milestone); to
suggest, take the LARGEST budget with >= n_min observations, split
good/bad by the top-``gamma`` fraction, sample candidates from the
good density and rank by good/bad density ratio.  A ``random_fraction``
of suggestions stays uniform for theoretical worst-case parity with
plain HyperBand.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.schedulers import HyperBandScheduler
from ray_tpu.tune.search.basic_variant import Searcher, _set_path
from ray_tpu.tune.search.tpe import (_FloatTPE, _flatten_domains,
                                     _get_path, _make_estimator)


class BOHBSearcher(Searcher):
    """Model-based config proposals conditioned on observation budget."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "min",
                 num_samples: int = 64, n_min: Optional[int] = None,
                 gamma: float = 0.25, n_candidates: int = 24,
                 random_fraction: float = 0.2,
                 seed: Optional[int] = None):
        assert mode in ("min", "max")
        self._space = param_space
        self._domains = _flatten_domains(param_space)
        self._estimators = {path: _make_estimator(d)
                            for path, d in self._domains}
        self.metric, self.mode = metric, mode
        self._budget_left = num_samples
        # Paper default: d+1 observations before the model activates.
        self.n_min = n_min if n_min is not None else len(self._domains) + 1
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.random_fraction = random_fraction
        self._rng = random.Random(seed)
        self._suggested: Dict[str, Dict] = {}
        # budget (training_iteration at record time) -> [(cfg, score)]
        self._obs: Dict[int, List[Tuple[Dict, float]]] = {}
        self.model_suggestions = 0  # observability: how often the model fired

    @property
    def total_trials(self) -> int:
        return self._budget_left

    # ------------------------------------------------------ observations
    def observe(self, config: Dict, budget: int, score: float) -> None:
        """Record a (config, score) pair measured AT ``budget``.  Called
        by HyperBandForBOHB at every rung record; on_trial_complete
        also lands here so the searcher works standalone."""
        self._obs.setdefault(int(budget), []).append(
            (config, float(score)))

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        cfg = self._suggested.get(trial_id)
        v = result.get(self.metric)
        if cfg is not None and v is not None:
            self.observe(cfg, result.get("training_iteration", 1),
                         float(v))

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result:
            return
        v = result.get(self.metric)
        if v is not None:
            self.observe(cfg, result.get("training_iteration", 1),
                         float(v))

    # ----------------------------------------------------------- suggest
    def _model_budget(self) -> Optional[int]:
        for b in sorted(self._obs, reverse=True):
            if len(self._obs[b]) >= self.n_min:
                return b
        return None

    def _random_config(self) -> Dict:
        cfg: Dict = {}
        for path, domain in self._domains:
            _set_path(cfg, path, domain.sample(self._rng))
        self._fill_constants(cfg, self._space, ())
        return cfg

    def _fill_constants(self, cfg, space, prefix):
        from ray_tpu.tune.search.sample import Domain
        for k, v in space.items():
            path = prefix + (k,)
            if isinstance(v, Domain):
                continue
            if isinstance(v, dict):
                self._fill_constants(cfg, v, path)
            else:
                _set_path(cfg, path, v)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._budget_left <= 0:
            return None
        self._budget_left -= 1
        budget = self._model_budget()
        if budget is None or self._rng.random() < self.random_fraction:
            cfg = self._random_config()
            self._suggested[trial_id] = cfg
            return cfg

        self.model_suggestions += 1
        hist = self._obs[budget]
        scores = np.array([s for _, s in hist])
        if self.mode == "max":
            scores = -scores
        n_good = max(1, int(math.ceil(self.gamma * len(scores))))
        order = np.argsort(scores)
        good = [hist[i][0] for i in order[:n_good]]
        bad = [hist[i][0] for i in order[n_good:]] or good

        cfg = {}
        for path, domain in self._domains:
            est = self._estimators[path]
            if isinstance(est, _FloatTPE):
                g = np.array([est._to_internal(_get_path(c, path))
                              for c in good])
                b = np.array([est._to_internal(_get_path(c, path))
                              for c in bad])
                cands = [est.sample_from(g, self._rng)
                         for _ in range(self.n_candidates)]
                ratios = [est.logpdf(x, g) - est.logpdf(x, b)
                          for x in cands]
                _set_path(cfg, path,
                          est._to_value(cands[int(np.argmax(ratios))]))
            else:
                g = [_get_path(c, path) for c in good]
                b = [_get_path(c, path) for c in bad]
                cands = [est.sample_from(g, self._rng)
                         for _ in range(self.n_candidates)]
                ratios = [est.logpdf(x, g) - est.logpdf(x, b)
                          for x in cands]
                _set_path(cfg, path, cands[int(np.argmax(ratios))])
        self._fill_constants(cfg, self._space, ())
        self._suggested[trial_id] = cfg
        return cfg


class HyperBandForBOHB(HyperBandScheduler):
    """Synchronous HyperBand that feeds rung-record observations to the
    attached BOHBSearcher AT THE BUDGET they were measured (reference:
    schedulers/hb_bohb.py — the pair's coupling point).  Without the
    link the searcher only hears end-of-trial results; with it every
    PAUSE/record advances the model at the rung's budget."""

    def __init__(self, searcher: Optional[BOHBSearcher] = None, **kw):
        super().__init__(**kw)
        self._bohb = searcher

    def attach_searcher(self, searcher: BOHBSearcher) -> None:
        self._bohb = searcher

    def on_trial_result(self, trial, result) -> str:
        decision = super().on_trial_result(trial, result)
        if self._bohb is not None:
            v = result.get(self.metric)
            if v is not None:
                self._bohb.observe(
                    dict(trial.config),
                    result.get("training_iteration", 1), float(v))
        return decision
