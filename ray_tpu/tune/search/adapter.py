"""The pluggable external-optimizer seam + searcher wrappers.

Reference: python/ray/tune/search/searcher.py (the Searcher contract
third-party algorithms implement), search/concurrency_limiter.py
(ConcurrencyLimiter), search/repeater.py (Repeater), and the 13
search/<lib>/ integrations (optuna, hyperopt, skopt, ...) — which all
reduce to the same ask/tell adaptation this module factors out once:

    optimizer.ask()          -> a config dict to evaluate
    optimizer.tell(cfg, val) -> observe a MINIMIZED objective value

Anything speaking that protocol drops into Tune via
``ExternalSearcher(optimizer, metric=..., mode=...)``.  ``OptunaSearch``
shows the adaptation for a real external library (gated on optuna
being installed); ``SkoptLikeGP`` is an in-tree ask/tell optimizer
built on scikit-learn's GP regressor proving the seam end to end with
a library that ships in this image.
"""

from __future__ import annotations

import copy
import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search.basic_variant import Searcher
from ray_tpu.tune.search.sample import Domain


class ExternalSearcher(Searcher):
    """Adapt any ask/tell optimizer to the Tune Searcher seam.

    The optimizer always MINIMIZES: with mode="max" the reported metric
    is negated before tell().  Errored trials release their suggestion
    slot without a tell (the reference's wrappers likewise skip failed
    trials rather than feeding them fabricated objective values)."""

    def __init__(self, optimizer: Any, metric: str, mode: str = "min",
                 num_samples: int = 64):
        assert mode in ("min", "max")
        if not (callable(getattr(optimizer, "ask", None))
                and callable(getattr(optimizer, "tell", None))):
            raise TypeError(
                f"{optimizer!r} does not speak the ask/tell protocol "
                "(needs .ask() -> dict and .tell(config, value))")
        self._opt = optimizer
        self.metric, self.mode = metric, mode
        self._budget = num_samples
        self._suggested: Dict[str, Dict] = {}

    @property
    def total_trials(self) -> int:
        return self._budget

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._budget <= 0:
            return None
        self._budget -= 1
        cfg = self._opt.ask()
        self._suggested[trial_id] = cfg
        return copy.deepcopy(cfg)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result:
            return
        value = result.get(self.metric)
        if value is None:
            return
        value = float(value)
        self._opt.tell(cfg, -value if self.mode == "max" else value)


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions of a wrapped searcher (reference:
    search/concurrency_limiter.py) — BO-style searchers degrade to
    random sampling when asked for many configs before any result
    lands; the cap keeps the model in the loop.

    At the cap, suggest() returns ``Searcher.DEFER``: the runner keeps
    the experiment alive and retries after results arrive (None would
    mark the search space exhausted)."""

    def __init__(self, searcher: Searcher, max_concurrent: int = 4):
        assert max_concurrent >= 1
        self._searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    @property
    def total_trials(self):
        return getattr(self._searcher, "total_trials", None)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if len(self._live) >= self.max_concurrent:
            return Searcher.DEFER
        cfg = self._searcher.suggest(trial_id)
        if cfg is not None and cfg is not Searcher.DEFER:
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        self._searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        self._live.discard(trial_id)
        self._searcher.on_trial_complete(trial_id, result, error=error)


class Repeater(Searcher):
    """Evaluate each suggested config ``repeat`` times and report the
    MEAN metric to the wrapped searcher (reference: search/repeater.py
    — noisy objectives need averaged observations or the model chases
    noise)."""

    def __init__(self, searcher: Searcher, repeat: int = 3):
        assert repeat >= 1
        self._searcher = searcher
        self.repeat = repeat
        self._group_of: Dict[str, Dict] = {}
        self._open_group: Optional[Dict] = None

    @property
    def total_trials(self):
        inner = getattr(self._searcher, "total_trials", None)
        return None if inner is None else inner * self.repeat

    def suggest(self, trial_id: str) -> Optional[Dict]:
        g = self._open_group
        if g is None or len(g["members"]) >= self.repeat:
            lead = f"{trial_id}-lead"
            cfg = self._searcher.suggest(lead)
            if cfg is None or cfg is Searcher.DEFER:
                return cfg
            g = {"lead": lead, "cfg": cfg, "members": [],
                 "scores": [], "errors": 0}
            self._open_group = g
        g["members"].append(trial_id)
        self._group_of[trial_id] = g
        return copy.deepcopy(g["cfg"])

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        g = self._group_of.pop(trial_id, None)
        if g is None:
            return
        metric = getattr(self._searcher, "metric", None)
        if error or not result or (metric is not None
                                   and result.get(metric) is None):
            g["errors"] += 1
        else:
            g["scores"].append(result)
        done = len(g["scores"]) + g["errors"]
        if done < self.repeat:
            return
        if not g["scores"]:
            self._searcher.on_trial_complete(g["lead"], error=True)
            return
        # Mean over the numeric metric; last result carries the rest.
        merged = dict(g["scores"][-1])
        if metric is not None:
            vals = [float(r[metric]) for r in g["scores"]]
            merged[metric] = sum(vals) / len(vals)
        self._searcher.on_trial_complete(g["lead"], merged)


class OptunaSearch(ExternalSearcher):
    """The optuna integration (reference: search/optuna/optuna_search.py)
    expressed through the ask/tell seam.  Gated: raises ImportError
    with guidance when optuna isn't installed."""

    def __init__(self, param_space: Dict, metric: str, mode: str = "min",
                 num_samples: int = 64, seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch needs the external 'optuna' package; "
                "install it or use the native TPESearcher/GPSearch "
                "(same algorithm family, no dependency)") from e

        sampler = optuna.samplers.TPESampler(seed=seed)
        study = optuna.create_study(direction="minimize", sampler=sampler)
        flat = _flatten_space(param_space)

        class _Opt:
            def __init__(self):
                self._trials: Dict[int, Any] = {}

            def ask(self):
                t = study.ask()
                cfg: Dict = {}
                for path, domain in flat:
                    _assign(cfg, path,
                            _optuna_suggest(t, ".".join(path), domain))
                cfg["__optuna_trial__"] = t._trial_id
                self._trials[t._trial_id] = t
                return cfg

            def tell(self, cfg, value):
                t = self._trials.pop(cfg.pop("__optuna_trial__"), None)
                if t is not None:
                    study.tell(t, value)

        super().__init__(_Opt(), metric, mode, num_samples)


def _optuna_suggest(trial, name: str, domain: Domain):
    """Map a tune sample Domain onto optuna's suggest_* API."""
    from ray_tpu.tune.search.sample import (Categorical, Float, Integer,
                                            Quantized)
    if isinstance(domain, Categorical):
        return trial.suggest_categorical(name, list(domain.categories))
    if isinstance(domain, Float):
        return trial.suggest_float(name, domain.lower, domain.upper,
                                   log=getattr(domain, "log", False))
    if isinstance(domain, Integer):
        return trial.suggest_int(name, domain.lower, domain.upper - 1)
    if isinstance(domain, Quantized):
        base = domain.inner
        if isinstance(base, Integer):
            return trial.suggest_int(name, base.lower, base.upper - 1,
                                     step=int(domain.q))
        return trial.suggest_float(name, base.lower, base.upper,
                                   step=float(domain.q))
    raise ValueError(f"unsupported domain for optuna: {domain!r}")


def _flatten_space(space: Dict, prefix=()) -> List[Tuple[tuple, Domain]]:
    out = []
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, Domain):
            out.append((path, v))
        elif isinstance(v, dict):
            out.extend(_flatten_space(v, path))
    return out


def _assign(cfg: Dict, path: tuple, value):
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


class SkoptLikeGP:
    """An ask/tell Bayesian optimizer on scikit-learn's
    GaussianProcessRegressor with expected improvement — a REAL external
    library (sklearn) integrated through the seam, proving a thirdparty
    optimizer needs zero Tune-internal knowledge.  Continuous Float
    dimensions only (categorical/int handling is what the native
    GPSearch provides)."""

    def __init__(self, bounds: Dict[str, Tuple[float, float]],
                 n_startup: int = 6, n_candidates: int = 256,
                 seed: Optional[int] = None):
        self.bounds = dict(bounds)
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._x: List[List[float]] = []
        self._y: List[float] = []

    def _sample(self) -> Dict:
        return {k: self._rng.uniform(lo, hi)
                for k, (lo, hi) in self.bounds.items()}

    def ask(self) -> Dict:
        if len(self._y) < self.n_startup:
            return self._sample()
        import numpy as np
        from sklearn.gaussian_process import GaussianProcessRegressor
        from sklearn.gaussian_process.kernels import Matern

        x = np.array(self._x)
        y = np.array(self._y)
        gp = GaussianProcessRegressor(kernel=Matern(nu=2.5),
                                      normalize_y=True,
                                      random_state=0)
        gp.fit(x, y)
        cand = np.array([[self._rng.uniform(lo, hi)
                          for lo, hi in self.bounds.values()]
                         for _ in range(self.n_candidates)])
        mu, sigma = gp.predict(cand, return_std=True)
        best = y.min()
        sigma = np.maximum(sigma, 1e-9)
        z = (best - mu) / sigma
        # Expected improvement for minimization.
        from math import erf, pi, sqrt
        phi = np.exp(-0.5 * z ** 2) / sqrt(2 * pi)
        big_phi = 0.5 * (1 + np.vectorize(erf)(z / sqrt(2)))
        ei = (best - mu) * big_phi + sigma * phi
        pick = cand[int(ei.argmax())]
        return {k: float(v) for k, v in zip(self.bounds, pick)}

    def tell(self, config: Dict, value: float) -> None:
        if not (isinstance(value, float) and math.isfinite(value)):
            return
        self._x.append([float(config[k]) for k in self.bounds])
        self._y.append(float(value))
