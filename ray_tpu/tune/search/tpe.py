"""TPE searcher: tree-structured Parzen estimator suggestion.

Reference: python/ray/tune/search/hyperopt (HyperOptSearch wraps
hyperopt's TPE); the external dependency is not available here, so the
algorithm itself is implemented natively (Bergstra et al. 2011): split
completed trials into good/bad by the gamma quantile, model each with a
Parzen window (KDE over floats / count smoothing over categoricals), and
suggest the candidate maximizing the density ratio l(x)/g(x).
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.basic_variant import Searcher, _set_path
from ray_tpu.tune.search.sample import (
    Categorical,
    Domain,
    Float,
    Integer,
    Quantized,
)


def _flatten_domains(space: Dict, prefix=()) -> List[Tuple[tuple, Domain]]:
    out = []
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, Domain):
            out.append((path, v))
        elif isinstance(v, dict):
            if set(v.keys()) == {"grid_search"}:
                raise ValueError("TPESearcher does not support "
                                 "grid_search markers; use Domains")
            out.extend(_flatten_domains(v, path))
    return out


def _get_path(cfg: Dict, path: tuple):
    for k in path:
        cfg = cfg[k]
    return cfg


class _FloatTPE:
    """1-D Parzen estimator over a (possibly log) float domain."""

    def __init__(self, lower, upper, log: bool, integer: bool = False,
                 q: float | None = None):
        self.log = log
        self.integer = integer
        self.q = q
        self.lo = math.log(lower) if log else lower
        self.hi = math.log(upper) if log else upper

    def _to_internal(self, v):
        return math.log(v) if self.log else float(v)

    def _to_value(self, x):
        v = math.exp(x) if self.log else x
        if self.q:
            v = round(v / self.q) * self.q
        if self.integer:
            v = int(round(v))
        return v

    def _kde(self, obs: np.ndarray):
        # Bandwidth: range-scaled Scott-style floor keeps the estimator
        # exploratory when observations cluster.
        width = self.hi - self.lo
        if len(obs) < 2:
            bw = width
        else:
            bw = max(np.std(obs) * len(obs) ** -0.2, width / 20.0)
        return obs, max(bw, 1e-12)

    def sample_from(self, obs: np.ndarray, rng: random.Random):
        centers, bw = self._kde(obs)
        c = centers[rng.randrange(len(centers))]
        x = rng.gauss(c, bw)
        return min(max(x, self.lo), self.hi)

    def logpdf(self, x: float, obs: np.ndarray) -> float:
        centers, bw = self._kde(obs)
        z = (x - centers) / bw
        comps = -0.5 * z * z - math.log(bw * math.sqrt(2 * math.pi))
        m = float(np.max(comps))
        return m + math.log(float(np.mean(np.exp(comps - m))) + 1e-300)


class _CatTPE:
    def __init__(self, categories: List):
        self.categories = categories

    def _counts(self, obs: List) -> np.ndarray:
        counts = np.ones(len(self.categories))  # +1 smoothing
        index = {self._key(c): i
                 for i, c in enumerate(self.categories)}
        for o in obs:
            counts[index[self._key(o)]] += 1
        return counts / counts.sum()

    @staticmethod
    def _key(v):
        return repr(v)

    def sample_from(self, obs: List, rng: random.Random):
        p = self._counts(obs)
        r = rng.random()
        return self.categories[int(np.searchsorted(np.cumsum(p), r))]

    def logpdf(self, v, obs: List) -> float:
        p = self._counts(obs)
        idx = [self._key(c) for c in self.categories].index(self._key(v))
        return math.log(p[idx])


def _make_estimator(domain: Domain):
    if isinstance(domain, Quantized):
        inner = domain.inner
        if isinstance(inner, Float):
            return _FloatTPE(inner.lower, inner.upper, inner.log,
                             q=domain.q)
        if isinstance(inner, Integer):
            return _FloatTPE(inner.lower, inner.upper - 1, False,
                             integer=True, q=domain.q)
        raise ValueError(f"unsupported quantized domain {inner!r}")
    if isinstance(domain, Float):
        return _FloatTPE(domain.lower, domain.upper, domain.log)
    if isinstance(domain, Integer):
        return _FloatTPE(domain.lower, max(domain.upper - 1,
                                           domain.lower), False,
                         integer=True)
    if isinstance(domain, Categorical):
        return _CatTPE(domain.categories)
    raise ValueError(f"unsupported domain for TPE: {domain!r}")


class TPESearcher(Searcher):
    def __init__(self, param_space: Dict, metric: str,
                 mode: str = "min", num_samples: int = 64,
                 n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        assert mode in ("min", "max")
        self._space = param_space
        self._domains = _flatten_domains(param_space)
        self._estimators = {path: _make_estimator(d)
                            for path, d in self._domains}
        self.metric, self.mode = metric, mode
        self._budget = num_samples
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._suggested: Dict[str, Dict] = {}
        self._history: List[Tuple[Dict, float]] = []

    @property
    def total_trials(self) -> int:
        return self._budget

    # ----------------------------------------------------------- suggest
    def _random_config(self) -> Dict:
        cfg: Dict = {}
        for path, domain in self._domains:
            _set_path(cfg, path, domain.sample(self._rng))
        # Carry through non-domain constants.
        self._fill_constants(cfg, self._space, ())
        return cfg

    def _fill_constants(self, cfg, space, prefix):
        for k, v in space.items():
            path = prefix + (k,)
            if isinstance(v, Domain):
                continue
            if isinstance(v, dict):
                self._fill_constants(cfg, v, path)
            else:
                _set_path(cfg, path, v)

    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._budget <= 0:
            return None
        self._budget -= 1
        if len(self._history) < self.n_startup:
            cfg = self._random_config()
            self._suggested[trial_id] = cfg
            return cfg

        scores = np.array([s for _, s in self._history])
        if self.mode == "max":
            scores = -scores
        n_good = max(1, int(math.ceil(self.gamma * len(scores))))
        order = np.argsort(scores)
        good = [self._history[i][0] for i in order[:n_good]]
        bad = [self._history[i][0] for i in order[n_good:]] or good

        cfg: Dict = {}
        for path, domain in self._domains:
            est = self._estimators[path]
            if isinstance(est, _FloatTPE):
                g_obs = np.array([est._to_internal(_get_path(c, path))
                                  for c in good])
                b_obs = np.array([est._to_internal(_get_path(c, path))
                                  for c in bad])
                cands = [est.sample_from(g_obs, self._rng)
                         for _ in range(self.n_candidates)]
                ratios = [est.logpdf(x, g_obs) - est.logpdf(x, b_obs)
                          for x in cands]
                best = cands[int(np.argmax(ratios))]
                _set_path(cfg, path, est._to_value(best))
            else:
                g_obs = [_get_path(c, path) for c in good]
                b_obs = [_get_path(c, path) for c in bad]
                cands = [est.sample_from(g_obs, self._rng)
                         for _ in range(self.n_candidates)]
                ratios = [est.logpdf(x, g_obs) - est.logpdf(x, b_obs)
                          for x in cands]
                _set_path(cfg, path, cands[int(np.argmax(ratios))])
        self._fill_constants(cfg, self._space, ())
        self._suggested[trial_id] = cfg
        return cfg

    # ----------------------------------------------------------- results
    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result \
                or self.metric not in result:
            return
        self._history.append((cfg, float(result[self.metric])))
