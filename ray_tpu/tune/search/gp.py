"""Native Gaussian-process Bayesian-optimization searcher.

Reference role: tune/search/bayesopt (the BayesOptSearch adapter over the
external `bayesian-optimization` package) — implemented natively with a
numpy RBF-kernel GP and expected-improvement acquisition, no external BO
dependency (same stance as the native TPE searcher and the PB2
scheduler's GP).

Continuous (`Float`, log-aware) and `Integer` dimensions are modeled in a
normalized [0,1] box; `Categorical` dimensions are one-hot.  Until
`n_startup` observations exist, suggestions are random.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.basic_variant import Searcher
from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.basic_variant import _set_path
from ray_tpu.tune.search.tpe import _flatten_domains, _get_path


class _Dim:
    """One search dimension <-> its normalized encoding."""

    def __init__(self, path: tuple, domain: Domain):
        self.path = path
        self.domain = domain
        if isinstance(domain, Categorical):
            self.width = len(domain.categories)
        elif isinstance(domain, (Float, Integer)):
            self.width = 1
        else:
            raise ValueError(
                f"GPSearch supports Float/Integer/Categorical domains; "
                f"got {type(domain).__name__} at {'.'.join(path)}")

    def encode(self, value) -> List[float]:
        d = self.domain
        if isinstance(d, Categorical):
            out = [0.0] * self.width
            out[d.categories.index(value)] = 1.0
            return out
        lo, hi = float(d.lower), float(d.upper)
        if isinstance(d, Float) and d.log:
            return [(math.log(value) - math.log(lo))
                    / max(math.log(hi) - math.log(lo), 1e-12)]
        if isinstance(d, Integer):
            # Integer.sample is exclusive-upper, so decode spans
            # [lo, hi-1]; normalize with the same span so
            # decode(encode(v)) == v.
            return [(float(value) - lo) / max(hi - 1 - lo, 1e-12)]
        return [(float(value) - lo) / max(hi - lo, 1e-12)]

    def decode(self, xs: List[float]):
        d = self.domain
        if isinstance(d, Categorical):
            return d.categories[int(np.argmax(xs))]
        u = min(1.0, max(0.0, xs[0]))
        lo, hi = float(d.lower), float(d.upper)
        if isinstance(d, Float):
            if d.log:
                return math.exp(math.log(lo)
                                + u * (math.log(hi) - math.log(lo)))
            return lo + u * (hi - lo)
        return int(round(lo + u * (hi - 1 - lo)))


class GPSearch(Searcher):
    def __init__(self, param_space: Dict, metric: str, mode: str = "max",
                 num_samples: int = 32, n_startup: int = 6,
                 n_candidates: int = 256, length_scale: float = 0.25,
                 xi: float = 0.01, seed: Optional[int] = None):
        assert mode in ("min", "max")
        self._space = param_space
        self.dims = [_Dim(path, d)
                     for path, d in _flatten_domains(param_space)]
        self.metric, self.mode = metric, mode
        self._budget = num_samples
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.ls = length_scale
        self.xi = xi
        self._rng = random.Random(seed)
        self._np_rng = np.random.RandomState(
            self._rng.randrange(1 << 31))
        self._suggested: Dict[str, Dict] = {}
        self._X: List[np.ndarray] = []
        self._y: List[float] = []

    @property
    def total_trials(self) -> int:
        return self._budget

    # ------------------------------------------------------------- encoding
    def _encode_cfg(self, cfg: Dict) -> np.ndarray:
        xs: List[float] = []
        for dim in self.dims:
            xs.extend(dim.encode(_get_path(cfg, dim.path)))
        return np.asarray(xs)

    def _decode_vec(self, x: np.ndarray) -> Dict:
        cfg: Dict = {}
        i = 0
        for dim in self.dims:
            _set_path(cfg, dim.path, dim.decode(list(x[i:i + dim.width])))
            i += dim.width
        self._fill_constants(cfg, self._space, ())
        return cfg

    def _fill_constants(self, cfg, space, prefix):
        for k, v in space.items():
            path = prefix + (k,)
            if isinstance(v, Domain):
                continue
            if isinstance(v, dict):
                self._fill_constants(cfg, v, path)
            else:
                _set_path(cfg, path, v)

    def _random_cfg(self) -> Dict:
        cfg: Dict = {}
        for dim in self.dims:
            _set_path(cfg, dim.path, dim.domain.sample(self._rng))
        self._fill_constants(cfg, self._space, ())
        return cfg

    # -------------------------------------------------------------- suggest
    def suggest(self, trial_id: str) -> Optional[Dict]:
        if self._budget <= 0:
            return None
        self._budget -= 1
        if len(self._y) < self.n_startup:
            cfg = self._random_cfg()
        else:
            cfg = self._gp_suggest()
        self._suggested[trial_id] = cfg
        return cfg

    def _gp_suggest(self) -> Dict:
        X = np.vstack(self._X)
        y = np.asarray(self._y, float)
        if self.mode == "min":
            y = -y
        y_mean, y_std = y.mean(), y.std() or 1.0
        yn = (y - y_mean) / y_std
        width = X.shape[1]
        cands = self._np_rng.uniform(size=(self.n_candidates, width))
        # A few perturbations of the incumbent sharpen exploitation.
        best_x = X[int(np.argmax(yn))]
        local = np.clip(best_x[None, :] + self._np_rng.normal(
            0, 0.1, size=(32, width)), 0, 1)
        cands = np.vstack([cands, local])

        def rbf(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * self.ls * self.ls))

        K = rbf(X, X) + 1e-3 * np.eye(len(X))
        Ks = rbf(cands, X)
        try:
            Kinv_y = np.linalg.solve(K, yn)
            mu = Ks @ Kinv_y
            Kinv_Ks = np.linalg.solve(K, Ks.T)
            var = np.clip(1.0 - (Ks * Kinv_Ks.T).sum(1), 1e-9, None)
        except np.linalg.LinAlgError:
            return self._random_cfg()
        sigma = np.sqrt(var)
        # Expected improvement over the incumbent.
        best = yn.max()
        z = (mu - best - self.xi) / sigma
        phi = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        Phi = 0.5 * (1 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = (mu - best - self.xi) * Phi + sigma * phi
        return self._decode_vec(cands[int(np.argmax(ei))])

    # -------------------------------------------------------------- results
    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result \
                or self.metric not in result:
            return
        self._X.append(self._encode_cfg(cfg))
        self._y.append(float(result[self.metric]))
