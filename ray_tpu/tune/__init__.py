"""Hyperparameter tuning (the reference's Ray Tune, SURVEY.md §2.3).

Experiment engine: trial generation (search algos), early-stopping and
population-based scheduling, execution as placement-group-backed actors,
checkpointing and fault tolerance — and the execution substrate for
Train's `fit()`.
"""

from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid, TuneConfig, Tuner, with_resources,
)
from ray_tpu.tune.trainable import (  # noqa: F401
    Trainable, with_parameters, wrap_function)
from ray_tpu.tune.analysis import ExperimentAnalysis  # noqa: F401
from ray_tpu.tune.progress_reporter import CLIReporter  # noqa: F401
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator, Searcher, choice, grid_search, loguniform,
    qrandint, quniform, randint, sample_from, uniform,
)
from ray_tpu.tune import schedulers  # noqa: F401
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler, AsyncHyperBandScheduler, FIFOScheduler,
    HyperBandScheduler, MedianStoppingRule, PB2,
    PopulationBasedTraining,
)
from ray_tpu.tune import storage  # noqa: F401
from ray_tpu.tune import logger  # noqa: F401
from ray_tpu.tune.logger import (  # noqa: F401
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    LoggerCallback,
    TBXLoggerCallback,
)
from ray_tpu.tune import stopper  # noqa: F401
from ray_tpu.tune.stopper import (  # noqa: F401
    CombinedStopper,
    ExperimentPlateauStopper,
    FunctionStopper,
    MaximumIterationStopper,
    NoopStopper,
    Stopper,
    TimeoutStopper,
    TrialPlateauStopper,
)
from ray_tpu.air import session as _session


def report(metrics: dict, checkpoint=None) -> None:
    """tune.report — alias of air.session.report (reference: tune/tune.py
    report shim)."""
    _session.report(metrics, checkpoint=checkpoint)


def get_checkpoint():
    return _session.get_checkpoint()


def run(trainable, *, config=None, num_samples: int = 1, stop=None,
        metric=None, mode: str = "max", search_alg=None, scheduler=None,
        max_concurrent_trials: int = 0, storage_path=None, name=None,
        checkpoint_config=None, failure_config=None, callbacks=None,
        verbose: int = 1, resources_per_trial=None, **_legacy):
    """Functional entry point (reference: tune/tune.py:129 tune.run).

    Unknown legacy kwargs are accepted WITH A WARNING so reference
    scripts run unmodified where semantics allow; kwargs whose
    silent omission would change results (resume/restore) are
    rejected with a pointer to the supported API."""
    from ray_tpu.air.config import CheckpointConfig, RunConfig
    for kw in ("resume", "restore"):
        if _legacy.pop(kw, None):
            raise TypeError(
                f"tune.run({kw}=...) is not supported here — use "
                "Tuner.restore(path, trainable).fit() to continue an "
                "interrupted experiment")
    # Legacy checkpoint kwargs map one-to-one onto CheckpointConfig;
    # dropping them would silently change results (no checkpoints ->
    # nothing to restore).
    freq = _legacy.pop("checkpoint_freq", None)
    at_end = _legacy.pop("checkpoint_at_end", None)
    keep = _legacy.pop("keep_checkpoints_num", None)
    if (freq or at_end or keep) and checkpoint_config is None:
        checkpoint_config = CheckpointConfig(
            checkpoint_frequency=freq or 0,
            checkpoint_at_end=bool(at_end),
            num_to_keep=keep)
    if _legacy:
        import logging
        logging.getLogger(__name__).warning(
            "tune.run: ignoring unsupported legacy kwargs %s",
            sorted(_legacy))
    if resources_per_trial and isinstance(resources_per_trial, dict):
        # Legacy lowercase keys ('cpu'/'gpu') would become custom
        # resources no node advertises; gpu maps to this framework's
        # accelerator (same aliasing as init(num_gpus=...)).
        _alias = {"cpu": "CPU", "gpu": "TPU", "GPU": "TPU"}
        resources_per_trial = {
            _alias.get(k, k): v for k, v in resources_per_trial.items()}
    if resources_per_trial:
        trainable = with_resources(trainable, resources_per_trial)
    tuner = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            search_alg=search_alg, scheduler=scheduler,
            max_concurrent_trials=max_concurrent_trials),
        run_config=RunConfig(
            name=name, storage_path=storage_path, stop=stop,
            checkpoint_config=checkpoint_config,
            failure_config=failure_config, callbacks=callbacks,
            verbose=verbose))
    return tuner.fit()

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("tune")
del _rlu
