"""Trial lifecycle + execution loop.

Reference: python/ray/tune/execution/trial_runner.py:234 (TrialRunner,
step :853) and ray_trial_executor.py:192 (trial actors inside placement
groups).  One actor per trial, gang resources via a placement group; the
driver loop waits on outstanding train() futures, feeds results to the
scheduler/searcher, and performs checkpoint/PBT-exploit/fault-tolerance
actions.
"""

from __future__ import annotations

import os
import tempfile
import time
import uuid
from typing import Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig, FailureConfig, RunConfig
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search.basic_variant import Searcher
from ray_tpu.tune.execution.placement_groups import (
    PlacementGroupFactory, resource_dict_to_pg_factory)
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP
from ray_tpu.tune.trainable import DONE, TRAINING_ITERATION, Trainable

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class _TrialActor:
    """The in-actor shell around a Trainable (reference: the Trainable IS
    the actor in Ray; here the shell keeps the trainable class pickled
    once per trial)."""

    def __init__(self, trainable_cls, config, trial_id, trial_name,
                 trial_dir):
        self._t: Trainable = trainable_cls(
            config=config, trial_id=trial_id, trial_name=trial_name,
            trial_dir=trial_dir)

    def ping(self):
        return True

    def train(self):
        return self._t.train()

    def save(self):
        return self._t.save()

    def latest_checkpoint(self):
        """User-facing checkpoint for the trial Result: the most recent
        session.report()-ed checkpoint for function trainables, else the
        trainable's own save_checkpoint payload (reference Tune always
        tracks the latest reported trial checkpoint)."""
        lc = getattr(self._t, "_latest_checkpoint", None)
        if lc is not None:
            return lc
        data = self._t.save_checkpoint()
        return Checkpoint.from_dict(data) if data else None

    def restore(self, ckpt):
        self._t.restore(ckpt)
        return True

    def reset(self, new_config):
        return self._t.reset(new_config)

    def stop(self):
        self._t.stop()
        return True


class Trial:
    def __init__(self, trainable_name: str, config: Dict,
                 pg_factory: PlacementGroupFactory, trial_dir: str,
                 stopping: Optional[Dict] = None,
                 trial_id: Optional[str] = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.name = f"{trainable_name}_{self.trial_id}"
        self.config = config
        self.pg_factory = pg_factory
        self.trial_dir = trial_dir
        self.status = PENDING
        self.actor = None
        self.pg = None
        self.last_result: Dict = {}
        self.checkpoint: Optional[Checkpoint] = None
        self.error: Optional[Exception] = None
        self.num_failures = 0
        self.pending_ref = None
        self.stopping = stopping or {}

    def should_stop(self, result: Dict) -> bool:
        if result.get(DONE):
            return True
        if callable(self.stopping):  # a tune.Stopper
            return bool(self.stopping(self.trial_id, result))
        for k, v in self.stopping.items():
            if k in result and result[k] >= v:
                return True
        return False

    def __repr__(self):
        return f"Trial({self.name}, {self.status})"


class TrialRunner:
    def __init__(self, trainable_cls, *, param_space: Optional[Dict] = None,
                 search_alg=None, scheduler=None, num_samples: int = 1,
                 max_concurrent: int = 0, metric: Optional[str] = None,
                 mode: str = "max", run_config: Optional[RunConfig] = None,
                 pg_factory: Optional[PlacementGroupFactory] = None,
                 trainable_name: str = "trainable"):
        from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
        self.trainable_cls = trainable_cls
        self.trainable_name = trainable_name
        self.search_alg = search_alg or BasicVariantGenerator(
            param_space or {}, num_samples=num_samples)
        self.scheduler = scheduler or sched_mod.FIFOScheduler()
        self.metric, self.mode = metric, mode
        self.max_concurrent = max_concurrent or int(
            os.environ.get("RT_TUNE_MAX_CONCURRENT", "8"))
        self.run_config = run_config or RunConfig()
        self.ckpt_config = (self.run_config.checkpoint_config
                            or CheckpointConfig())
        self.failure_config = (self.run_config.failure_config
                               or FailureConfig())
        from ray_tpu.tune.logger import _dispatch as _cb_dispatch
        self.callbacks = list(self.run_config.callbacks or [])
        if self.run_config.verbose >= 2:
            from ray_tpu.tune.progress_reporter import CLIReporter
            if not any(isinstance(cb, CLIReporter)
                       for cb in self.callbacks):
                self.callbacks.append(CLIReporter())
        self._cb = lambda hook, *a: _cb_dispatch(self.callbacks, hook, *a)
        self._cb_setup_done = False
        self.pg_factory = pg_factory
        base = self.run_config.storage_path or tempfile.mkdtemp(
            prefix="rt_tune_")
        exp_name = self.run_config.name or f"exp_{uuid.uuid4().hex[:6]}"
        from ray_tpu.tune.storage import get_storage, is_remote_uri
        if is_remote_uri(base):
            # Remote storage URI: work out of a local scratch dir and
            # sync state through the storage backend (reference:
            # tune/syncer.py — checkpoints/state survive the head node).
            self.storage = get_storage(base)
            self._storage_prefix = exp_name
            self.experiment_dir = os.path.join(
                tempfile.mkdtemp(prefix="rt_tune_scratch_"), exp_name)
        else:
            base = base[len("file://"):] if base.startswith("file://") \
                else base
            self.storage = None
            self._storage_prefix = exp_name
            self.experiment_dir = os.path.join(base, exp_name)
        os.makedirs(self.experiment_dir, exist_ok=True)
        self.trials: List[Trial] = []
        self._stopping = self._normalize_stop(self.run_config.stop)
        self._stop_all_requested = False

    @staticmethod
    def _normalize_stop(stop):
        """dict stays a dict (cheap per-trial check); Stopper/callable
        become a shared tune.Stopper whose stop_all() ends the whole
        experiment (reference: tune/stopper/)."""
        if stop is None or isinstance(stop, dict):
            return dict(stop or {})
        from ray_tpu.tune.stopper import normalize_stopper
        return normalize_stopper(stop)

    # ------------------------------------------- experiment-level resume
    def _save_experiment_state(self):
        """Persist trial metadata so a crashed/interrupted experiment can
        resume (reference: tune.run(resume=...) replaying trial state
        from the experiment dir)."""
        import pickle
        state = [{"trial_id": t.trial_id, "name": t.name,
                  "config": t.config, "status": t.status,
                  "last_result": t.last_result,
                  "checkpoint": t.checkpoint,
                  "trial_dir": t.trial_dir} for t in self.trials]
        path = os.path.join(self.experiment_dir, "experiment_state.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        os.replace(tmp, path)
        self._publish_to_dashboard()
        if self.storage is not None:
            # Sync up: trial metadata + driver-held checkpoints ride in
            # the state blob, so this one upload makes the experiment
            # resumable from the storage backend alone.
            self.storage.upload_file(
                path, f"{self._storage_prefix}/experiment_state.pkl")

    @staticmethod
    def _jsonable(obj):
        import json
        try:
            json.dumps(obj)
            return obj
        except (TypeError, ValueError):
            if isinstance(obj, dict):
                return {str(k): TrialRunner._jsonable(v)
                        for k, v in obj.items()}
            if isinstance(obj, (list, tuple)):
                return [TrialRunner._jsonable(v) for v in obj]
            return repr(obj)

    def _publish_to_dashboard(self):
        """Best-effort experiment summary to the GCS KV ("tune"
        namespace) so the dashboard's Tune view works cross-host
        without filesystem access (reference: the reference dashboard's
        tune module reads experiment state through the head)."""
        import json
        import math
        try:
            now = time.time()
            # Throttle: this publish is a blocking GCS round-trip on the
            # result-processing path; cap it at ~1/2s (the final publish
            # at experiment end goes through because status changes
            # force _save_experiment_state anyway).
            if now - getattr(self, "_last_publish", 0.0) < 2.0 \
                    and not all(t.status in ("TERMINATED", "ERROR")
                                for t in self.trials):
                return
            self._last_publish = now
            import ray_tpu
            w = ray_tpu._private.worker.global_worker
            if w is None:
                return
            trials = []
            for t in self.trials:
                # Non-finite floats would serialize as bare NaN/Infinity
                # tokens (Python's extended JSON), which the browser's
                # JSON.parse rejects — drop them.
                last = {k: v for k, v in (t.last_result or {}).items()
                        if isinstance(v, (int, float, str, bool))
                        and (not isinstance(v, float) or math.isfinite(v))}
                trials.append({"trial_id": t.trial_id, "name": t.name,
                               "status": t.status,
                               "config": self._jsonable(t.config),
                               "last_result": last})
            rec = {"name": self._storage_prefix,
                   "dir": self.experiment_dir,
                   "updated_at": time.time(),
                   "trials": trials}
            w._run(w._gcs_request(
                "kv_put", {"ns": "tune",
                           "key": self._storage_prefix.encode(),
                           "value": json.dumps(rec).encode(),
                           "overwrite": True}))
        except Exception:
            pass  # observability must never sink the experiment

    def restore_experiment_state(self) -> bool:
        """Reload saved trials: TERMINATED ones keep their results;
        unfinished ones are re-seeded PENDING (restored from their last
        driver-held checkpoint when present).  Returns True if state was
        found."""
        import pickle
        path = os.path.join(self.experiment_dir, "experiment_state.pkl")
        if self.storage is not None:
            rel = f"{self._storage_prefix}/experiment_state.pkl"
            if not self.storage.exists(rel):
                return False
            self.storage.download_file(rel, path)
        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            saved = pickle.load(f)
        for s in saved:
            trial = Trial(self.trainable_name, s["config"],
                          self.pg_factory or resource_dict_to_pg_factory(
                              None),
                          self.experiment_dir, stopping=self._stopping)
            trial.trial_id = s["trial_id"]
            trial.name = s["name"]
            trial.trial_dir = s["trial_dir"]
            trial.last_result = s["last_result"]
            trial.checkpoint = s["checkpoint"]
            if s["status"] == TERMINATED:
                trial.status = TERMINATED
            else:
                trial.status = PENDING
            self.trials.append(trial)
            self.scheduler.on_trial_add(trial)
        # The search space was consumed by the original run; restored
        # experiments replay the saved trial set only.
        self._exhausted = True
        return True

    # ---------------------------------------------------------------- setup
    def _make_trial(self) -> "Trial | str | None":
        # Tri-state: a Trial, None (space exhausted), or
        # Searcher.DEFER (capacity-limited searcher; retry later).
        # The id handed to the searcher IS the trial's id, so BO-style
        # searchers can pair on_trial_complete results with their
        # suggestions (reference: search/searcher.py contract).
        tid = uuid.uuid4().hex[:8]
        cfg = self.search_alg.suggest(tid)
        if cfg is None:
            return None
        if cfg == Searcher.DEFER:
            # Concurrency-limited searcher: capacity exists but the
            # searcher wants results before suggesting more.  NOT
            # exhaustion — retry next loop pass.
            self._deferred = True
            return Searcher.DEFER
        self._deferred = False
        pgf = self.pg_factory or resource_dict_to_pg_factory(
            cfg.pop("__resources__", None) if isinstance(cfg, dict) else None)
        trial = Trial(self.trainable_name, cfg, pgf, self.experiment_dir,
                      stopping=self._stopping, trial_id=tid)
        trial.trial_dir = os.path.join(self.experiment_dir, trial.name)
        os.makedirs(trial.trial_dir, exist_ok=True)
        self.trials.append(trial)
        self.scheduler.on_trial_add(trial)
        return trial

    def _start_trial(self, trial: Trial, restore: bool = False,
                     defer_ping: bool = False):
        if trial.pg is None:
            trial.pg = trial.pg_factory.create(name=f"pg_{trial.trial_id}")
        ok = ray_tpu.wait_placement_group_ready(trial.pg, timeout=120)
        if not ok:
            raise RuntimeError(f"placement group for {trial.name} not ready")
        self._launch_trial(trial, restore=restore, defer_ping=defer_ping)

    def _launch_trial(self, trial: Trial, restore: bool = False,
                      defer_ping: bool = False):
        """Create the trial actor inside its (ready) placement group."""
        head = trial.pg_factory.head_bundle
        actor_cls = ray_tpu.remote(_TrialActor)
        trial.actor = actor_cls.options(
            num_cpus=head.get("CPU", 0),
            resources={k: v for k, v in head.items() if k != "CPU"},
            placement_group=trial.pg, placement_group_bundle_index=0,
        ).remote(self.trainable_cls, trial.config, trial.trial_id,
                 trial.name, trial.trial_dir)
        # Block until the actor is live: concurrently-started trials must
        # begin training at the same wall-clock time, or schedulers that
        # compare trials at a rung (ASHA) can watch one trial sprint to
        # completion while its peer's worker is still cold-starting.
        # (_fill_trials defers this to overlap cold-starts across trials.)
        if not defer_ping:
            ray_tpu.get(trial.actor.ping.remote(), timeout=120)
        if restore and trial.checkpoint is not None:
            ray_tpu.get(trial.actor.restore.remote(trial.checkpoint),
                        timeout=300)
        trial.status = RUNNING
        trial.pending_ref = None
        self._cb("on_trial_start", trial)

    def _notify_trial_error(self, trial: Trial):
        """A trial died outside the normal result path: BOTH consumers
        must hear it — the searcher (or it leaks the suggestion slot)
        and the scheduler (or a synchronous HyperBand bracket waits on
        the dead member forever)."""
        self.search_alg.on_trial_complete(trial.trial_id, error=True)
        self.scheduler.on_trial_complete(trial, None)

    def _stop_trial(self, trial: Trial, status: str,
                    notify_cb: bool = True):
        trial.status = status
        if not notify_cb:
            pass  # caller intends to retry: loggers keep runs open
        elif status == TERMINATED:
            self._cb("on_trial_complete", trial)
        elif status == ERROR:
            self._cb("on_trial_error", trial)
        if trial.actor is not None:
            try:
                ray_tpu.get(trial.actor.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        if trial.pg is not None:
            try:
                from ray_tpu.util.placement_group import (
                    remove_placement_group)
                remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None

    # ---------------------------------------------------------------- loop
    _exhausted = False
    # True while the searcher answers DEFER (capacity exists but it
    # wants results first) — for stall decisions this is equivalent to
    # exhaustion: no new trial can arrive until something completes.
    _deferred = False

    def is_finished(self) -> bool:
        active = any(t.status in (PENDING, RUNNING, PAUSED)
                     for t in self.trials)
        return not active and self._exhausted

    def _apply_scheduler_actions(self):
        """Drain synchronous-scheduler verdicts (HyperBand brackets):
        resume promoted PAUSED trials, terminate demoted ones."""
        pop = getattr(self.scheduler, "pop_actions", None)
        if pop is None:
            return
        resume, stop = pop()
        for trial in stop:
            if trial.status in (PAUSED, RUNNING, PENDING):
                self._stop_trial(trial, TERMINATED)
                self.search_alg.on_trial_complete(trial.trial_id,
                                                  trial.last_result)
        for trial in resume:
            if trial.status == PAUSED:
                # Re-enter through the restored-trial path (checkpoint
                # was taken at pause time).
                trial.status = PENDING

    def run(self, result_callback: Optional[Callable] = None) -> List[Trial]:
        """Drive all trials to completion; returns the trial list."""
        if not self._cb_setup_done:
            # Here, not in __init__: setup() may read experiment_dir /
            # storage / trials, which don't exist mid-construction.
            self._cb_setup_done = True
            self._cb("setup", self)
        try:
            return self._run_loop(result_callback)
        finally:
            # Fires on fail_fast raises too, so loggers flush/close
            # even when the experiment aborts.
            self._cb("on_experiment_end", self.trials)

    def _run_loop(self, result_callback: Optional[Callable]) -> List[Trial]:
        stuck_since = None
        stuck_resumes = 0
        while True:
            # Poll experiment-level stoppers every pass, not only on
            # results: TimeoutStopper must fire during long or hung
            # iterations too.
            if not self._stop_all_requested and callable(self._stopping) \
                    and self._stopping.stop_all():
                self._stop_all_requested = True
            if self._stop_all_requested:
                for t in self.trials:
                    if t.status in (RUNNING, PAUSED, PENDING):
                        self._stop_trial(t, TERMINATED)
                break
            self._apply_scheduler_actions()
            self._start_restored_trials()
            self._fill_trials()
            running = [t for t in self.trials if t.status == RUNNING]
            if running:
                # Real progress since the last wedge: a later,
                # independent benign stall deserves the cheap
                # resume-all again, not immediate termination.
                stuck_resumes = 0
            if not running:
                paused = [t for t in self.trials if t.status == PAUSED]
                pending = [t for t in self.trials
                           if t.status == PENDING]
                if self._exhausted and not self._staged() \
                        and not paused and not pending:
                    break
                # A deferring searcher can't unblock an all-paused
                # cluster either (paused trials never complete, so its
                # in-flight slots never free): treat it like exhaustion
                # for the stall escape or ConcurrencyLimiter +
                # synchronous HyperBand deadlock.
                stalled = self._exhausted or self._deferred
                if paused and not pending and stalled \
                        and not self._staged():
                    # Every live trial is paused and nothing new can
                    # ever arrive: a synchronous bracket is waiting on
                    # members that will never come (under-full bracket
                    # template, or a death it somehow missed).  The
                    # condition is already stable, so advance NOW — no
                    # stall — and only fall back to resume-everything
                    # if the scheduler cannot make progress.
                    force = getattr(self.scheduler, "force_advance",
                                    None)
                    if force is not None and force():
                        stuck_since = None
                        continue
                    if stuck_since is None:
                        stuck_since = time.monotonic()
                    elif time.monotonic() - stuck_since > 5.0:
                        # Bounded: resume-everything at most once.  If
                        # the resumed trials just re-pause (scheduler
                        # still can't advance), terminating them is the
                        # only move that doesn't churn actors and
                        # placement groups forever.
                        if stuck_resumes == 0:
                            stuck_resumes = 1
                            print("[tune] WARNING: scheduler stuck with "
                                  f"{len(paused)} paused trials and no "
                                  "progress; resuming all paused trials "
                                  "once (will terminate if it recurs)")
                            for t in paused:
                                t.status = PENDING
                        else:
                            print("[tune] WARNING: scheduler stuck "
                                  "again after resume-all fallback; "
                                  f"terminating {len(paused)} paused "
                                  "trials")
                            for t in paused:
                                # Abnormal exit: ERROR (not TERMINATED)
                                # so the partial last_result is neither
                                # a searcher observation nor eligible
                                # as the experiment's best result.
                                self._stop_trial(t, ERROR)
                                self._notify_trial_error(t)
                        stuck_since = None
                # Staged trials are waiting for reservations to land;
                # don't spin hot while nothing is training.
                time.sleep(0.2)
                continue
            stuck_since = None
            # Submit one train() per running trial without an outstanding
            # future.
            for t in running:
                if t.pending_ref is None:
                    t.pending_ref = t.actor.train.remote()
            refs = [t.pending_ref for t in running]
            by_ref = {t.pending_ref: t for t in running}
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=60.0)
            for ref in ready:
                trial = by_ref[ref]
                trial.pending_ref = None
                try:
                    result = ray_tpu.get(ref, timeout=60.0)
                except Exception as e:
                    self._handle_failure(trial, e)
                    continue
                self._handle_result(trial, result, result_callback)
            self._apply_exploits()
        return self.trials

    def _start_restored_trials(self):
        """PENDING trials seeded by restore_experiment_state (they never
        go through _make_trial)."""
        pending = [t for t in self.trials if t.status == PENDING]
        for trial in pending:
            if sum(t.status == RUNNING for t in self.trials) \
                    >= self.max_concurrent:
                break
            try:
                self._start_trial(trial, restore=trial.checkpoint
                                  is not None)
            except Exception as e:
                # Through _stop_trial like every other error path: it
                # tears down the actor/PG and fires on_trial_error.
                trial.error = e
                self._stop_trial(trial, ERROR)
                self._notify_trial_error(trial)

    def _staged(self) -> List[Trial]:
        return [t for t in self.trials
                if t.status == PENDING and t.pg is not None
                and t.actor is None]

    def _fill_trials(self):
        # Stage new trials — create their placement groups WITHOUT
        # blocking on readiness, so more trials than free resources never
        # stalls the result loop (reference: RayTrialExecutor stages PGs
        # via _pg_manager and promotes trials as reservations land).
        while not self._exhausted and \
                sum(t.status == RUNNING or (t.status == PENDING
                                            and t.pg is not None)
                    for t in self.trials) < self.max_concurrent:
            trial = self._make_trial()
            if trial is None:
                self._exhausted = True
                break
            if trial == Searcher.DEFER:
                break
            trial.pg = trial.pg_factory.create(
                name=f"pg_{trial.trial_id}")
            trial.staged_at = time.monotonic()
        # Promote every staged trial whose 2-phase reservation is done.
        started: List[Trial] = []
        any_running = any(t.status == RUNNING for t in self.trials)
        for trial in self._staged():
            if not ray_tpu.wait_placement_group_ready(trial.pg,
                                                      timeout=0.05):
                if any_running:
                    # Queued behind live trials — restart the idle clock
                    # so only time with the cluster otherwise idle counts
                    # toward infeasibility.
                    trial.staged_at = time.monotonic()
                elif time.monotonic() - getattr(trial, "staged_at", 0) \
                        > 300:
                    # Overdemand guard: the reservation cannot land even
                    # with the cluster idle — the trial is infeasible.
                    # error BEFORE _stop_trial: on_trial_error
                    # callbacks read it.
                    trial.error = RuntimeError(
                        f"placement group for {trial.name} cannot be "
                        f"scheduled")
                    self._stop_trial(trial, ERROR)
                    # The searcher paired a suggestion with this trial id;
                    # it must hear the trial ended or it leaks the slot
                    # (BO searchers never learn the outcome otherwise).
                    self._notify_trial_error(trial)
                    if self.failure_config.fail_fast:
                        raise trial.error
                continue
            try:
                # Create all actors first (spawns overlap), await liveness
                # below so N cold-starts cost one spawn latency, not N.
                self._launch_trial(trial, defer_ping=True)
                started.append(trial)
            except Exception as e:
                trial.error = e
                self._stop_trial(trial, ERROR)
                self._notify_trial_error(trial)
                if self.failure_config.fail_fast:
                    raise
        for trial in started:
            try:
                ray_tpu.get(trial.actor.ping.remote(), timeout=120)
            except Exception as e:
                trial.error = e
                self._stop_trial(trial, ERROR)
                self._notify_trial_error(trial)
                if self.failure_config.fail_fast:
                    raise

    def _handle_result(self, trial: Trial, result: Dict,
                       result_callback: Optional[Callable]):
        # Merge so a bare final/done result doesn't erase reported metrics.
        trial.last_result = {**trial.last_result, **result}
        self._cb("on_trial_result", trial, result)
        if result_callback is not None:
            result_callback(trial, result)
        self.search_alg.on_trial_result(trial.trial_id, result)
        it = result.get(TRAINING_ITERATION, 0)
        freq = self.ckpt_config.checkpoint_frequency
        if freq and it % freq == 0 and not result.get(DONE):
            try:
                trial.checkpoint = ray_tpu.get(trial.actor.save.remote(),
                                               timeout=300)
            except Exception:
                pass
        if trial.should_stop(result):
            decision = STOP
        else:
            decision = self.scheduler.on_trial_result(trial, result)
        if callable(self._stopping) and self._stopping.stop_all():
            # Experiment-level stop (TimeoutStopper/ExperimentPlateau):
            # the run loop terminates every live trial on its next pass.
            self._stop_all_requested = True
            decision = STOP
        if decision == STOP:
            if self.ckpt_config.checkpoint_at_end and trial.actor:
                try:
                    trial.checkpoint = ray_tpu.get(
                        trial.actor.save.remote(), timeout=300)
                except Exception:
                    pass
            elif trial.actor:
                # Terminal: expose the latest reported checkpoint in the
                # Result even without an explicit checkpoint config.
                try:
                    ckpt = ray_tpu.get(
                        trial.actor.latest_checkpoint.remote(), timeout=300)
                    if ckpt is not None:
                        trial.checkpoint = ckpt
                except Exception:
                    pass
            self.search_alg.on_trial_complete(trial.trial_id, result)
            self.scheduler.on_trial_complete(trial, result)
            self._stop_trial(trial, TERMINATED)
        elif decision == PAUSE:
            # Synchronous-bracket pause (HyperBand): checkpoint, then
            # RELEASE the actor + placement group so waiting bracket
            # peers can use the resources; resume goes through the
            # restored-trial path.  A failed save means the trial
            # CANNOT be paused losslessly — route it through the
            # failure path (retry/ERROR) instead of silently pausing
            # with a stale checkpoint, which would resume the trial at
            # the wrong training depth relative to its bracket peers.
            try:
                trial.checkpoint = ray_tpu.get(trial.actor.save.remote(),
                                               timeout=300)
            except Exception as e:
                self._handle_failure(trial, e)
                return
            self._stop_trial(trial, PAUSED)
        try:
            self._save_experiment_state()
        except Exception:
            pass

    def _handle_failure(self, trial: Trial, err: Exception):
        trial.num_failures += 1
        trial.error = err
        will_retry = (trial.num_failures
                      <= self.failure_config.max_failures)
        # A retryable failure is not a trial END: loggers must keep
        # their tracker runs open (ending a wandb/mlflow run is
        # permanent — the retried trial could never log again).
        self._stop_trial(trial, ERROR, notify_cb=not will_retry)
        if will_retry:
            # Restart from the last driver-held checkpoint.
            try:
                self._start_trial(trial, restore=True)
                trial.error = None
                return  # restarted: the searcher will hear the real end
            except Exception as e:
                # The failed restart may have created a fresh PG (and
                # actor): tear them down through _stop_trial — which
                # also fires on_trial_error, since now it IS the end.
                trial.error = e
                self._stop_trial(trial, ERROR)
        elif self.failure_config.fail_fast:
            self.search_alg.on_trial_complete(trial.trial_id, error=True)
            self.scheduler.on_trial_complete(trial, None)
            raise err
        self.search_alg.on_trial_complete(trial.trial_id, error=True)
        # Synchronous schedulers (HyperBand) must hear about the death
        # or their bracket waits on this trial forever.
        self.scheduler.on_trial_complete(trial, None)

    def _apply_exploits(self):
        pbt = self.scheduler
        exploits = getattr(pbt, "pending_exploits", None)
        if not exploits:
            return
        by_id = {t.trial_id: t for t in self.trials}
        for victim_id, donor_id in list(exploits.items()):
            exploits.pop(victim_id)
            victim, donor = by_id.get(victim_id), by_id.get(donor_id)
            if not victim or not donor or victim.status != RUNNING \
                    or donor.status != RUNNING:
                continue
            try:
                if donor.pending_ref is not None:
                    # The in-flight result must go through the normal result
                    # path: silently dropping it loses metrics and — if it
                    # was the fn's final report — leaves the resubmitted
                    # train() blocked on an already-consumed sentinel.
                    res = ray_tpu.get(donor.pending_ref, timeout=300)
                    donor.pending_ref = None
                    self._handle_result(donor, res, None)
                    if donor.status != RUNNING:
                        continue
                    donor.pending_ref = donor.actor.train.remote()
                ckpt = ray_tpu.get(donor.actor.save.remote(), timeout=300)
                new_config = pbt.explore(donor.config)
                if victim.pending_ref is not None:
                    # Same rule as the donor: in-flight results go through
                    # the result path so metrics reach searcher/scheduler
                    # and a DONE trial completes instead of being exploited.
                    res = ray_tpu.get(victim.pending_ref, timeout=300)
                    victim.pending_ref = None
                    self._handle_result(victim, res, None)
                    if victim.status != RUNNING:
                        continue
                ray_tpu.get(victim.actor.reset.remote(new_config),
                            timeout=300)
                ray_tpu.get(victim.actor.restore.remote(ckpt), timeout=300)
                victim.config = new_config
                victim.checkpoint = ckpt
            except Exception:
                continue


def best_trial(trials: List[Trial], metric: str, mode: str = "max"):
    done = [t for t in trials if t.last_result.get(metric) is not None]
    if not done:
        return None
    key = lambda t: t.last_result[metric]  # noqa: E731
    return max(done, key=key) if mode == "max" else min(done, key=key)
