"""PlacementGroupFactory: declarative trial resources (reference:
python/ray/tune/execution/placement_groups.py:58)."""

from __future__ import annotations

from typing import Dict, List


class PlacementGroupFactory:
    def __init__(self, bundles: List[Dict[str, float]],
                 strategy: str = "PACK"):
        if not bundles:
            raise ValueError("need at least one bundle")
        self.bundles = [dict(b) for b in bundles]
        self.strategy = strategy

    @property
    def head_bundle(self) -> Dict[str, float]:
        return self.bundles[0]

    def create(self, name: str = ""):
        from ray_tpu.util.placement_group import placement_group
        return placement_group(self.bundles, strategy=self.strategy,
                               name=name)

    def __repr__(self):
        return (f"PlacementGroupFactory({self.bundles}, "
                f"strategy={self.strategy!r})")


def resource_dict_to_pg_factory(resources: Dict) -> PlacementGroupFactory:
    bundle = {k: v for k, v in (resources or {"CPU": 1}).items() if v}
    return PlacementGroupFactory([bundle or {"CPU": 1}])
