"""Stopper family: programmatic stopping criteria for trials and whole
experiments (reference: python/ray/tune/stopper/ — Stopper base
stopper.py:7, MaximumIterationStopper, TimeoutStopper, FunctionStopper,
TrialPlateauStopper, ExperimentPlateauStopper, CombinedStopper).

Contract: `stopper(trial_id, result) -> bool` stops ONE trial;
`stopper.stop_all() -> bool` ends the whole experiment (checked by the
TrialRunner after every result)."""

from __future__ import annotations

import abc
import collections
import time
from typing import Callable, Dict, Optional

import numpy as np


class Stopper(abc.ABC):
    @abc.abstractmethod
    def __call__(self, trial_id: str, result: Dict) -> bool:
        """Should this trial stop now?"""

    def stop_all(self) -> bool:
        """Should the whole experiment stop?"""
        return False


class NoopStopper(Stopper):
    def __call__(self, trial_id, result):
        return False


class FunctionStopper(Stopper):
    """Wrap a plain `fn(trial_id, result) -> bool`."""

    def __init__(self, function: Callable[[str, Dict], bool]):
        self._fn = function

    def __call__(self, trial_id, result):
        return bool(self._fn(trial_id, result))

    @classmethod
    def is_valid_function(cls, fn) -> bool:
        return callable(fn) and not isinstance(fn, Stopper)


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self._max_iter = max_iter
        self._iter: Dict[str, int] = collections.defaultdict(int)

    def __call__(self, trial_id, result):
        self._iter[trial_id] += 1
        return self._iter[trial_id] >= self._max_iter


class TimeoutStopper(Stopper):
    """Stop the WHOLE experiment after a wall-clock budget."""

    def __init__(self, timeout: float):
        if hasattr(timeout, "total_seconds"):  # datetime.timedelta
            timeout = timeout.total_seconds()
        self._timeout = float(timeout)
        self._start = time.monotonic()

    def __call__(self, trial_id, result):
        return False

    def stop_all(self):
        return time.monotonic() - self._start >= self._timeout


class TrialPlateauStopper(Stopper):
    """Stop a trial when its metric's moving std plateaus (reference:
    stopper/trial_plateau.py)."""

    def __init__(self, metric: str, std: float = 0.01,
                 num_results: int = 4, grace_period: int = 4,
                 metric_threshold: Optional[float] = None,
                 mode: Optional[str] = None):
        self._metric = metric
        self._std = std
        self._num_results = num_results
        self._grace = grace_period
        self._threshold = metric_threshold
        self._mode = mode
        self._window: Dict[str, collections.deque] = \
            collections.defaultdict(
                lambda: collections.deque(maxlen=num_results))
        self._count: Dict[str, int] = collections.defaultdict(int)

    def __call__(self, trial_id, result):
        if self._metric not in result:
            return False
        v = result[self._metric]
        self._window[trial_id].append(v)
        self._count[trial_id] += 1
        if self._count[trial_id] < self._grace:
            return False
        if len(self._window[trial_id]) < self._num_results:
            return False
        if self._threshold is not None:
            if self._mode == "min" and v > self._threshold:
                return False
            if self._mode == "max" and v < self._threshold:
                return False
        return float(np.std(self._window[trial_id])) <= self._std


class ExperimentPlateauStopper(Stopper):
    """Stop EVERYTHING when the best `top` trial scores plateau
    (reference: stopper/experiment_plateau.py)."""

    def __init__(self, metric: str, std: float = 0.001, top: int = 10,
                 mode: str = "min", patience: int = 0):
        self._metric = metric
        self._std = std
        self._top = top
        self._mode = mode
        self._patience = patience
        self._scores: list = []
        self._strikes = 0
        self._plateau = False

    def __call__(self, trial_id, result):
        if self._metric not in result:
            return False
        self._scores.append(result[self._metric])
        self._scores.sort(reverse=(self._mode == "max"))
        del self._scores[self._top:]
        if len(self._scores) == self._top and \
                float(np.std(self._scores)) <= self._std:
            self._strikes += 1
        else:
            self._strikes = 0
        self._plateau = self._strikes > self._patience
        return self._plateau

    def stop_all(self):
        return self._plateau


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self._stoppers = stoppers

    def __call__(self, trial_id, result):
        return any(s(trial_id, result) for s in self._stoppers)

    def stop_all(self):
        return any(s.stop_all() for s in self._stoppers)


class _DictStopper(Stopper):
    """The classic `stop={"metric": bound}` dict as a Stopper."""

    def __init__(self, criteria: Dict):
        self._criteria = dict(criteria)

    def __call__(self, trial_id, result):
        return any(k in result and result[k] >= v
                   for k, v in self._criteria.items())


def normalize_stopper(stop) -> Stopper:
    """dict / callable / Stopper / None -> Stopper (reference: the
    stop-argument coercion in tune.run)."""
    if stop is None:
        return NoopStopper()
    if isinstance(stop, Stopper):
        return stop
    if isinstance(stop, dict):
        return _DictStopper(stop)
    if FunctionStopper.is_valid_function(stop):
        return FunctionStopper(stop)
    raise TypeError(
        f"stop must be a dict, callable, or Stopper; got {type(stop)}")
