"""Tune experiment callbacks + per-trial loggers.

Reference: python/ray/tune/callback.py (Callback lifecycle hooks
dispatched by the trial runner) and python/ray/tune/logger/
(LoggerCallback with log_trial_start/result/end; json.py, csv.py,
tensorboardx.py writing result.json / progress.csv / TB event files
into each trial's directory).

Same contract, one simplification: hooks receive (trial, result)
directly rather than the reference's (iteration, trials, trial, ...)
tuple — the runner here is single-threaded, so callbacks can read any
cross-trial state they need from the runner they were handed at
setup.
"""

from __future__ import annotations

import csv
import json
import logging
import numbers
import os
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class Callback:
    """Experiment-level lifecycle hooks (reference: tune/callback.py).

    All hooks are optional; exceptions are caught and logged by the
    dispatcher so a misbehaving callback cannot sink the experiment.
    """

    def setup(self, runner) -> None:
        """Called once before the first trial starts."""

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


class LoggerCallback(Callback):
    """Per-trial logger seam (reference: tune/logger/logger.py
    LoggerCallback): subclasses implement log_trial_* and this base
    adapts them to the Callback lifecycle, tracking which trials are
    open so log_trial_start runs once per trial (restarts included)."""

    def __init__(self):
        self._started: set = set()

    def log_trial_start(self, trial) -> None:
        pass

    def log_trial_result(self, iteration: int, trial, result: Dict) -> None:
        pass

    def log_trial_end(self, trial, failed: bool = False) -> None:
        pass

    # --- Callback adaptation ----------------------------------------
    def on_trial_start(self, trial) -> None:
        if trial.trial_id not in self._started:
            self._started.add(trial.trial_id)
            self.log_trial_start(trial)

    def on_trial_result(self, trial, result: Dict) -> None:
        if trial.trial_id not in self._started:
            self._started.add(trial.trial_id)
            self.log_trial_start(trial)
        self.log_trial_result(
            int(result.get("training_iteration", 0)), trial, result)

    def on_trial_complete(self, trial) -> None:
        self._started.discard(trial.trial_id)
        self.log_trial_end(trial, failed=False)

    def on_trial_error(self, trial) -> None:
        self._started.discard(trial.trial_id)
        self.log_trial_end(trial, failed=True)


def _json_safe(value):
    try:
        json.dumps(value)
        return value
    except (TypeError, ValueError):
        return repr(value)


class JsonLoggerCallback(LoggerCallback):
    """result.json: one JSON object per reported result, plus
    params.json with the trial config (reference: tune/logger/json.py
    — the format `tune.ExperimentAnalysis` and the reference's own
    resume tooling read)."""

    def __init__(self):
        super().__init__()
        self._files: Dict[str, object] = {}

    def log_trial_start(self, trial) -> None:
        os.makedirs(trial.trial_dir, exist_ok=True)
        with open(os.path.join(trial.trial_dir, "params.json"), "w") as f:
            json.dump({k: _json_safe(v) for k, v in trial.config.items()},
                      f)
        self._files[trial.trial_id] = open(
            os.path.join(trial.trial_dir, "result.json"), "a")

    def on_experiment_end(self, trials) -> None:
        # Aborted experiments (fail_fast) leave running trials' files
        # open — close everything.
        for f in self._files.values():
            f.close()
        self._files.clear()

    def log_trial_result(self, iteration, trial, result) -> None:
        f = self._files.get(trial.trial_id)
        if f is None:
            return
        json.dump({k: _json_safe(v) for k, v in result.items()}, f)
        f.write("\n")
        f.flush()

    def log_trial_end(self, trial, failed=False) -> None:
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()


def _flatten(d: Dict, prefix: str = "") -> Dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


class CSVLoggerCallback(LoggerCallback):
    """progress.csv with a header fixed at the first result
    (reference: tune/logger/csv.py — later keys are dropped, matching
    the reference's DictWriter extrasaction behavior)."""

    def __init__(self):
        super().__init__()
        self._writers: Dict[str, csv.DictWriter] = {}
        self._files: Dict[str, object] = {}

    def log_trial_start(self, trial) -> None:
        os.makedirs(trial.trial_dir, exist_ok=True)
        path = os.path.join(trial.trial_dir, "progress.csv")
        # Reopening after a trial restart: rows must keep matching the
        # file's EXISTING header, not whatever keys the first
        # post-restart result happens to carry.
        if os.path.exists(path) and os.path.getsize(path) > 0:
            with open(path, newline="") as existing:
                header = next(csv.reader(existing), None)
            f = open(path, "a")
            if header:
                self._writers[trial.trial_id] = csv.DictWriter(
                    f, fieldnames=header, extrasaction="ignore")
        else:
            f = open(path, "a")
        self._files[trial.trial_id] = f

    def log_trial_result(self, iteration, trial, result) -> None:
        f = self._files.get(trial.trial_id)
        if f is None:
            return
        flat = _flatten(result)
        writer = self._writers.get(trial.trial_id)
        if writer is None:
            writer = csv.DictWriter(f, fieldnames=sorted(flat),
                                    extrasaction="ignore")
            self._writers[trial.trial_id] = writer
            writer.writeheader()
        writer.writerow({k: flat.get(k) for k in writer.fieldnames})
        f.flush()

    def log_trial_end(self, trial, failed=False) -> None:
        self._writers.pop(trial.trial_id, None)
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()

    def on_experiment_end(self, trials) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()
        self._writers.clear()


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard event files via tensorboardX (reference:
    tune/logger/tensorboardx.py TBXLoggerCallback): numeric scalars
    per result at step=training_iteration, trial config as hparams on
    trial end."""

    def __init__(self):
        super().__init__()
        try:
            from tensorboardX import SummaryWriter
        except ImportError as e:  # pragma: no cover - baked in here
            raise RuntimeError(
                "TBXLoggerCallback requires tensorboardX") from e
        self._writer_cls = SummaryWriter
        self._writers: Dict[str, object] = {}
        self._last: Dict[str, Dict] = {}

    def log_trial_start(self, trial) -> None:
        os.makedirs(trial.trial_dir, exist_ok=True)
        self._writers[trial.trial_id] = self._writer_cls(
            logdir=trial.trial_dir, flush_secs=5)

    def log_trial_result(self, iteration, trial, result) -> None:
        w = self._writers.get(trial.trial_id)
        if w is None:
            return
        step = iteration or int(result.get("training_iteration", 0))
        for k, v in _flatten(result).items():
            if isinstance(v, numbers.Number) and not isinstance(v, bool):
                w.add_scalar(f"ray/tune/{k}", float(v), global_step=step)
        self._last[trial.trial_id] = result
        w.flush()

    def on_experiment_end(self, trials) -> None:
        for tid in list(self._writers):
            w = self._writers.pop(tid)
            self._last.pop(tid, None)
            w.close()

    def log_trial_end(self, trial, failed=False) -> None:
        w = self._writers.pop(trial.trial_id, None)
        if w is None:
            return
        last = self._last.pop(trial.trial_id, {})
        hparams = {k: v for k, v in _flatten(trial.config).items()
                   if isinstance(v, (numbers.Number, str, bool))}
        metrics = {f"ray/tune/{k}": float(v)
                   for k, v in _flatten(last).items()
                   if isinstance(v, numbers.Number)
                   and not isinstance(v, bool)}
        if hparams and metrics:
            try:
                w.add_hparams(hparams, metrics)
            except Exception:
                logger.debug("hparams logging failed", exc_info=True)
        w.close()


DEFAULT_LOGGERS = (JsonLoggerCallback, CSVLoggerCallback,
                   TBXLoggerCallback)


def _dispatch(callbacks: List[Callback], hook: str, *args) -> None:
    """Run one hook across callbacks; failures are logged, never
    raised (a logger must not sink the experiment)."""
    for cb in callbacks or ():
        try:
            getattr(cb, hook)(*args)
        except Exception:
            logger.warning("tune callback %s.%s failed",
                           type(cb).__name__, hook, exc_info=True)
