"""Post-hoc experiment analysis from logged trial files.

Reference: python/ray/tune/analysis/experiment_analysis.py
(ExperimentAnalysis — reconstructs an experiment from its directory:
per-trial params.json + result.json written by the JSON logger, best
trial/config/logdir selection by metric/mode, pandas dataframes).

Works on any experiment run with ``JsonLoggerCallback`` (and on a
live ``ResultGrid``'s storage directory after ``fit()`` returns).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional


class ExperimentAnalysis:
    def __init__(self, experiment_dir: str,
                 default_metric: Optional[str] = None,
                 default_mode: str = "max"):
        if not os.path.isdir(experiment_dir):
            raise ValueError(f"no such experiment dir: {experiment_dir}")
        self._dir = experiment_dir
        self.default_metric = default_metric
        if default_mode not in ("max", "min"):
            raise ValueError(f"mode must be max|min: {default_mode}")
        self.default_mode = default_mode
        self._trials: Dict[str, Dict] = {}  # trial_dir -> data
        self._load()

    def _load(self):
        for d in sorted(glob.glob(os.path.join(self._dir, "*"))):
            result_file = os.path.join(d, "result.json")
            if not os.path.isdir(d) or not os.path.exists(result_file):
                continue
            results = []
            with open(result_file) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            results.append(json.loads(line))
                        except json.JSONDecodeError:
                            continue  # torn tail line of a live run
            params = {}
            params_file = os.path.join(d, "params.json")
            if os.path.exists(params_file):
                try:
                    params = json.load(open(params_file))
                except json.JSONDecodeError:
                    pass
            self._trials[d] = {"config": params, "results": results}
        if not self._trials:
            raise ValueError(
                f"{self._dir} has no trial dirs with result.json — was "
                "the experiment run with JsonLoggerCallback?")

    # --- accessors ---------------------------------------------------
    @property
    def trial_dirs(self) -> List[str]:
        return list(self._trials)

    def trial_dataframes(self) -> Dict[str, "object"]:
        """trial_dir -> pandas DataFrame of its full result history."""
        import pandas as pd
        return {d: pd.DataFrame(t["results"])
                for d, t in self._trials.items()}

    def dataframe(self, metric: Optional[str] = None,
                  mode: Optional[str] = None) -> "object":
        """One row per trial: config (flattened as ``config/<k>``) +
        its best-or-last result (reference: dataframe(metric, mode) —
        metric=None takes the last result)."""
        import pandas as pd

        from ray_tpu.tune.logger import _flatten
        rows = []
        for d, t in self._trials.items():
            row = dict(self._pick(t, metric, mode) or {})
            for k, v in _flatten(t["config"]).items():
                row[f"config/{k}"] = v
            row["logdir"] = d
            rows.append(row)
        return pd.DataFrame(rows)

    def _pick(self, trial: Dict, metric: Optional[str],
              mode: Optional[str]) -> Optional[Dict]:
        results = [r for r in trial["results"]]
        if not results:
            return None
        if metric is None:
            return results[-1]
        # NaN-reporting results (diverged trials) are excluded: every
        # comparison against NaN is False, so a NaN would otherwise
        # win max() and best-trial selection outright.
        scored = [r for r in results
                  if metric in r and r[metric] == r[metric]]
        if not scored:
            return None
        key = lambda r: r[metric]  # noqa: E731
        return (max if (mode or self.default_mode) == "max"
                else min)(scored, key=key)

    def _best_trial_dir(self, metric: Optional[str],
                        mode: Optional[str]) -> str:
        metric = metric or self.default_metric
        if metric is None:
            raise ValueError(
                "pass metric= (or set default_metric) to rank trials")
        mode = mode or self.default_mode
        best_d, best_v = None, None
        for d, t in self._trials.items():
            picked = self._pick(t, metric, mode)
            if picked is None:
                continue
            v = picked[metric]
            better = (best_v is None or
                      (v > best_v if mode == "max" else v < best_v))
            if better:
                best_d, best_v = d, v
        if best_d is None:
            raise ValueError(f"no trial ever reported {metric!r}")
        return best_d

    def get_best_logdir(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> str:
        return self._best_trial_dir(metric, mode)

    def get_best_config(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Dict:
        return self._trials[self._best_trial_dir(metric, mode)]["config"]

    @property
    def best_config(self) -> Dict:
        return self.get_best_config()

    @property
    def best_logdir(self) -> str:
        return self.get_best_logdir()

    def get_best_checkpoint(self, logdir: Optional[str] = None,
                            metric: Optional[str] = None,
                            mode: Optional[str] = None):
        """Latest checkpoint directory under the best (or given)
        trial dir, if trial checkpoints were materialized to disk."""
        d = logdir or self._best_trial_dir(metric, mode)

        def _index(path: str):
            tail = os.path.basename(path).rsplit("_", 1)[-1]
            # Numeric when possible: lexicographic order would rank
            # checkpoint_9 above checkpoint_12.
            return (0, int(tail)) if tail.isdigit() else (1, tail)

        ckpts = sorted(glob.glob(os.path.join(d, "checkpoint_*")),
                       key=_index)
        return ckpts[-1] if ckpts else None
