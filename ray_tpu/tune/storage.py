"""Tune's storage seam — shared with workflow (see util/storage.py)."""

from ray_tpu.util.storage import (  # noqa: F401
    LocalStorage,
    MemStorage,
    Storage,
    get_storage,
    is_remote_uri,
    register_storage,
)
