"""Device mesh construction with named parallelism axes.

Axes (any may be 1):
  dp    data parallel (pure replication of params, sharded batch)
  fsdp  fully-sharded data parallel (params sharded over this axis too)
  tp    tensor parallel (attention heads / mlp hidden sharded)
  pp    pipeline parallel (layer stages)
  sp    sequence/context parallel (ring attention over sequence shards)
  ep    expert parallel (MoE experts sharded)

The reference has no analogue — its parallelism stops at gang-scheduled
process groups (SURVEY.md §2.4).  On TPU the mesh IS the cluster-of-chips
abstraction: axis order below is chosen so the innermost (fastest-varying)
axes carry the heaviest collectives and land on ICI neighbours.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclass
class MeshSpec:
    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    @property
    def world_size(self) -> int:
        return math.prod(self.axis_sizes().values())

    def nontrivial_axes(self) -> tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if getattr(self, a) > 1)

    @classmethod
    def infer(cls, n_devices: int, tp: int = 1, pp: int = 1, sp: int = 1,
              ep: int = 1, fsdp: int = 1) -> "MeshSpec":
        """Fill dp with whatever devices remain after the explicit axes."""
        denom = tp * pp * sp * ep * fsdp
        if n_devices % denom != 0:
            raise ValueError(f"{n_devices} devices not divisible by "
                             f"tp*pp*sp*ep*fsdp={denom}")
        return cls(dp=n_devices // denom, fsdp=fsdp, tp=tp, pp=pp, sp=sp,
                   ep=ep)


def make_mesh(spec: MeshSpec, devices=None):
    """Build a jax Mesh laid out so tp (heaviest collective traffic) varies
    fastest across physically adjacent devices."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = spec.world_size
    if len(devices) < n:
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    devices = _ici_order(devices)[:n]
    shape = tuple(spec.axis_sizes()[a] for a in AXIS_ORDER)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def _ici_order(devices):
    """Sort devices so consecutive entries are ICI neighbours (by mesh
    coordinates when the backend exposes them)."""
    def key(d):
        coords = getattr(d, "coords", None)
        if coords is not None:
            return (getattr(d, "slice_index", 0) or 0, tuple(coords))
        return (0, (d.id,))
    return sorted(devices, key=key)


def mesh_shape_dict(mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))
