"""Expert parallelism: MoE layers with experts sharded over the `ep` mesh
axis and token routing via `lax.all_to_all` (absent from the reference —
SURVEY.md §2.4 lists EP as delegated/absent).

Dispatch is the capacity-bucketed dense formulation (Switch/GShard style):
top-1 gating builds a [tokens, experts, capacity] one-hot dispatch tensor,
tokens travel to their expert's shard with a single all-to-all over `ep`
(the MoE-heavy collective, which rides ICI), expert MLPs run as one batched
einsum per shard (MXU-friendly: one big matmul instead of per-expert
loops), and a second all-to-all brings outputs home.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def top1_dispatch(xf, gate_w, e_local: int, ep_rank, ep_size: int,
                  capacity_factor: float, dtype=None):
    """Shared top-1 capacity-bucketed routing (Switch/GShard style).

    xf: [N, D] tokens; gate_w: [D, E_total].  Returns (dispatch, combine),
    both [N, E_local, C], restricted to this shard's experts
    [ep_rank*e_local, (ep_rank+1)*e_local).  With ep_size=1/ep_rank=0 this
    is the single-shard routing.  Gating runs in fp32 for stable argmax/
    softmax regardless of the compute dtype."""
    n_tok = xf.shape[0]
    n_exp = e_local * ep_size
    logits = xf.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)
    gate_val = jnp.take_along_axis(gates, expert_idx[:, None], axis=1)[:, 0]
    capacity = max(1, int(capacity_factor * n_tok / n_exp))
    onehot = jax.nn.one_hot(expert_idx, n_exp, dtype=jnp.int32)
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1)
    keep = pos < capacity
    local_expert = expert_idx - ep_rank * e_local
    in_local = (local_expert >= 0) & (local_expert < e_local) & keep
    local_oh = (jax.nn.one_hot(jnp.clip(local_expert, 0, e_local - 1),
                               e_local) * in_local[:, None])
    dispatch = local_oh[..., None] * jax.nn.one_hot(pos, capacity)[:, None, :]
    if dtype is not None:
        dispatch = dispatch.astype(dtype)
    combine = dispatch * gate_val.astype(dispatch.dtype)[:, None, None]
    return dispatch, combine


def _moe_sharded(x, gate_w, w_in, w_out, axis_name, capacity_factor):
    """Per-shard body.  x (tokens) replicated over `ep`; experts sharded:
    w_in/w_out are the local [E_local, ...] slices.  Every shard computes
    the (identical) routing, runs only its own experts' buckets, and a
    single psum recombines token outputs — the collective XLA emits is the
    reduce over ICI, the EP equivalent of the all-to-all in token-sharded
    deployments (that variant lands with dp x ep meshes in Train)."""
    ep = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    e_local = w_in.shape[0]
    b, t, d = x.shape
    xf = x.reshape(b * t, d)
    # dispatch/combine over the LOCAL expert slice only: [N, E_local, C]
    dispatch, combine = top1_dispatch(xf, gate_w, e_local, my, ep,
                                      capacity_factor)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xf)  # [E_local, C, D]
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
    out = jnp.einsum("ecf,efd->ecd", h, w_out)  # [E_local, C, D]
    y = jnp.einsum("nec,ecd->nd", combine, out)
    return lax.psum(y, axis_name).reshape(b, t, d)


def expert_parallel_moe(x, gate_w, w_in, w_out, mesh=None,
                        axis_name: str = "ep",
                        capacity_factor: float = 2.0):
    """Top-1 MoE layer with experts sharded over `axis_name`.

    x: [B, T, D] (batch may itself be dp-sharded outside);
    gate_w: [D, E]; w_in: [E, D, F]; w_out: [E, F, D] with E divisible by
    the ep axis size.
    """
    if mesh is None:
        return _moe_sharded(x, gate_w, w_in, w_out, axis_name,
                            capacity_factor)
    from jax import shard_map
    fn = shard_map(
        functools.partial(_moe_sharded, axis_name=axis_name,
                          capacity_factor=capacity_factor),
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name), P(axis_name)),
        out_specs=P())
    return fn(x, gate_w, w_in, w_out)


def reference_moe(x, gate_w, w_in, w_out, capacity_factor: float = 2.0):
    """Single-device oracle with the same capacity semantics."""
    return _moe_sharded_single(x, gate_w, w_in, w_out, capacity_factor)


def _moe_sharded_single(x, gate_w, w_in, w_out, capacity_factor):
    b, t, d = x.shape
    xf = x.reshape(b * t, d)
    dispatch, combine = top1_dispatch(xf, gate_w, w_in.shape[0], 0, 1,
                                      capacity_factor)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xf)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
    out = jnp.einsum("ecf,efd->ecd", h, w_out)
    y = jnp.einsum("nec,ecd->nd", combine, out)
    return y.reshape(b, t, d)
