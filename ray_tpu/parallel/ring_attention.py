"""Ring attention: exact attention over sequence shards with ICI-ring K/V
rotation (sequence/context parallelism).

Absent from the reference (SURVEY.md §5 long-context: nothing in-tree).
Design: inside `shard_map` over the `sp` axis each device holds a sequence
block of Q, K, V.  K/V blocks rotate around the ring via `lax.ppermute`
(one ICI hop per step, overlapping with the block attention compute, which
XLA schedules as async collective-permute), while a numerically-stable
online-softmax accumulator (running max + normalizer, flash-attention
style) folds in each visited block.  After `sp` steps every Q block has
attended to the full sequence — memory stays O(T/sp) per device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


def _block_update(q, k, v, o, m, l, q_offset, k_offset, causal, scale):
    """Fold one K/V block into the online-softmax accumulator.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]
    o: [B, Tq, H, D]; m, l: [B, H, Tq]
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(tq)
        k_pos = k_offset + jnp.arange(tk)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(scores - m_new[..., None])
    # Fully-masked rows: p would be exp(-inf - -inf); m_new stays _NEG_INF
    # and p = exp(scores - _NEG_INF) would overflow — clamp.
    p = jnp.where((scores <= _NEG_INF / 2) & (m_new[..., None] <= _NEG_INF / 2),
                  0.0, p)
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o_new, m_new, l_new


def _ring_attention_sharded(q, k, v, axis_name: str, causal: bool,
                            scale: float, kv_repeat: int = 1):
    """Per-shard body (runs under shard_map).

    kv_repeat > 1 = grouped-query attention: k/v carry Hkv = H/kv_repeat
    heads and ROTATE at that size (the ring wire and the K/V cache stay
    Hkv-sized); each step broadcasts the received block to the full head
    count locally before the online-softmax update."""
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[1]
    b, _, h, d = q.shape

    # Accumulators derive from q so their shard_map varying-axis type
    # matches the per-step updates (scan requires carry types to agree).
    o = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full_like(q[..., 0].transpose(0, 2, 1), _NEG_INF,
                      dtype=jnp.float32)
    l = jnp.zeros_like(m)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (my_idx - i) % axis_size
        k_blk, v_blk = k_cur, v_cur
        if kv_repeat > 1:  # local broadcast, after the ring transfer
            k_blk = jnp.repeat(k_cur, kv_repeat, axis=2)
            v_blk = jnp.repeat(v_cur, kv_repeat, axis=2)
        o, m, l = _block_update(
            q, k_blk, v_blk, o, m, l,
            q_offset=my_idx * t_local,
            k_offset=src * t_local,
            causal=causal, scale=scale)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o, m, l, k, v), jnp.arange(axis_size))
    l = jnp.maximum(l, 1e-20)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name: str = "sp",
                   causal: bool = True, scale: float | None = None):
    """Exact attention with sequence sharded over `axis_name`.

    Args are [batch, seq, heads, head_dim]; seq must divide by the axis
    size.  Called OUTSIDE shard_map (wraps itself), or pass mesh=None and
    axis_name to use inside an existing shard_map body.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if mesh is None:
        return _ring_attention_sharded(q, k, v, axis_name, causal, scale)
    from jax import shard_map
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_sharded, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True, scale=None):
    """Dense single-device attention (test oracle)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v).astype(q.dtype)
