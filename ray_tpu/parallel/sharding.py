"""Logical-axis sharding rules: map parameter/activation logical axes onto
mesh axes, in the flax `logical axis` style but framework-neutral.

Rules follow the standard megatron/fsdp decomposition:
  embed        -> tp          (vocab-sharded embedding)
  heads        -> tp          (attention heads)
  mlp          -> tp          (ffn hidden)
  layers       -> pp          (stage dimension, when stacked)
  batch        -> (dp, fsdp)  (activations)
  seq          -> sp          (activations, long-context)
  experts      -> ep
  model params additionally shard their largest remaining dim over fsdp.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

DEFAULT_RULES: dict[str, object] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "heads": "tp",
    "kv": None,
    "embed": None,
    "embed_fsdp": "fsdp",
    "mlp": "tp",
    "vocab": "tp",
    "layers": "pp",
    "experts": "ep",
    "stage": "pp",
}


def logical_to_mesh_axes(logical_axes: tuple, rules: dict | None = None):
    rules = {**DEFAULT_RULES, **(rules or {})}
    return P(*(rules.get(a) if a is not None else None
               for a in logical_axes))


def with_logical_constraint(x, logical_axes: tuple, mesh=None,
                            rules: dict | None = None):
    """Annotate an intermediate with a sharding constraint (inside jit)."""
    spec = logical_to_mesh_axes(logical_axes, rules)
    return jax.lax.with_sharding_constraint(
        x, spec if mesh is None else NamedSharding(mesh, spec))


def shard_params(params, logical_specs, mesh, rules: dict | None = None):
    """Device-put a pytree of params according to per-leaf logical axes.

    `logical_specs` mirrors `params` with tuples of logical axis names."""
    def _place(leaf, axes):
        spec = logical_to_mesh_axes(axes, rules)
        return jax.device_put(leaf, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(_place, params, logical_specs)


def named_sharding(mesh, *axes):
    return NamedSharding(mesh, P(*axes))
