"""SPMD pipeline parallelism: layer stages sharded over the `pp` mesh axis,
microbatches streamed through with `lax.ppermute` (GPipe schedule expressed
as a collective program, praxis-style — no per-stage processes).

Absent from the reference (SURVEY.md §2.4: no pipeline engine in-tree).
Each device holds the parameters of its stage.  For M microbatches and S
stages the loop runs M+S-1 ticks; at tick t stage s computes microbatch
t-s (when valid) and permutes its activation to stage s+1.  The bubble is
(S-1)/(M+S-1); compute and the single-hop ICI permute overlap.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _pipeline_sharded(stage_params, x_mb, stage_fn, axis_name):
    """Body under shard_map.

    stage_params: this stage's params (leading stage dim of size 1 stripped)
    x_mb: [M, mb, ...] full microbatched input (replicated across pp)
    Returns [M, mb, ...] outputs (valid on every rank after final psum).
    """
    s_size = lax.psum(1, axis_name)
    s_idx = lax.axis_index(axis_name)
    n_mb = x_mb.shape[0]
    ticks = n_mb + s_size - 1
    perm = [(i, i + 1) for i in range(s_size - 1)]

    stream0 = jnp.zeros_like(x_mb[0])
    outputs0 = jnp.zeros_like(x_mb)
    # Stage outputs vary over pp (they depend on the stage's params);
    # promote the zero-initialized carries to the same varying type.
    try:
        stream0 = lax.pcast(stream0, (axis_name,), to="varying")
        outputs0 = lax.pcast(outputs0, (axis_name,), to="varying")
    except (AttributeError, TypeError, ValueError):
        pass

    def tick(carry, t):
        stream, outputs = carry
        mb_idx = jnp.clip(t - s_idx, 0, n_mb - 1)
        inp = jnp.where(s_idx == 0, x_mb[jnp.clip(t, 0, n_mb - 1)], stream)
        out = stage_fn(stage_params, inp)
        valid = (t - s_idx >= 0) & (t - s_idx < n_mb)
        # Last stage records its finished microbatch.
        rec = valid & (s_idx == s_size - 1)
        outputs = jnp.where(
            rec,
            outputs.at[mb_idx].set(out),
            outputs)
        stream_next = lax.ppermute(out, axis_name, perm)
        return (stream_next, outputs), None

    (_, outputs), _ = lax.scan(tick, (stream0, outputs0),
                               jnp.arange(ticks))
    # Only the last stage holds real outputs; share them with all stages
    # (callers usually need the loss everywhere for the backward pass).
    outputs = jnp.where(s_idx == s_size - 1, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis_name)


def pipeline_spmd(stage_fn, stacked_params, x, num_microbatches: int,
                  mesh=None, axis_name: str = "pp",
                  params_stage_specs=None):
    """Run `stage_fn(params, x) -> y` as a pipeline over `axis_name`.

    stacked_params: pytree whose leaves have a leading stage dimension of
    size S (the pp axis size); each device gets its own stage's slice.
    x: [batch, ...] global input; split into `num_microbatches`.
    Output has the same shape as stage_fn's output batched over x.
    """
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(f"batch {b} not divisible by microbatches "
                         f"{num_microbatches}")
    x_mb = x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])

    def body(params, xm):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        return _pipeline_sharded(params, xm, stage_fn, axis_name)

    if mesh is None:
        stripped = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
        out = _pipeline_sharded(stripped, x_mb, stage_fn, axis_name)
        return out.reshape(b, *out.shape[2:])

    from jax import shard_map
    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis_name, *([None] * (p.ndim - 1))), stacked_params)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P()), out_specs=P())
    out = fn(stacked_params, x_mb)
    return out.reshape(b, *out.shape[2:])
