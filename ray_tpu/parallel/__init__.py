"""TPU-first parallelism: mesh axes as the unit of scale.

The reference delegates tensor/pipeline/sequence/expert parallelism to user
frameworks (SURVEY.md §2.4: TP/PP/SP/EP are absent in-tree; its value-add is
gang scheduling + NCCL groups).  Here they are first-class: a MeshSpec
declares dp/fsdp/tp/pp/sp/ep axes, sharding rules map parameters and
activations onto them, and the long-context/pipeline/expert building blocks
compile to XLA collectives over ICI.
"""

from ray_tpu.parallel.mesh import MeshSpec, make_mesh  # noqa: F401
from ray_tpu.parallel.sharding import (  # noqa: F401
    logical_to_mesh_axes,
    shard_params,
    with_logical_constraint,
)
from ray_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from ray_tpu.parallel.pipeline import pipeline_spmd  # noqa: F401
from ray_tpu.parallel.moe import expert_parallel_moe  # noqa: F401
