"""Job submission: run a shell entrypoint as a supervised cluster job.

Reference: dashboard/modules/job/job_manager.py — JobManager (:320)
starting a JobSupervisor actor (:109) per job; the supervisor runs the
entrypoint as a subprocess, streams its output, and records status
transitions (PENDING -> RUNNING -> SUCCEEDED/FAILED/STOPPED) that clients
poll.  Status + logs live in the GCS KV so they survive the submitting
client.
"""

from __future__ import annotations

import pickle
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

JOBS_NS = "job_submissions"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobSupervisor:
    """Detached actor owning one job subprocess (reference:
    job_manager.py:109 JobSupervisor)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Optional[Dict[str, str]] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.env_vars = env_vars or {}
        self.proc = None
        self._log_chunks: List[str] = []
        self._status = JobStatus.PENDING
        self._message = ""
        self._save()

    def _save(self):
        # Non-blocking KV push: run() executes ON the worker's event loop,
        # so a blocking _run here would deadlock the actor.
        w = ray_tpu._private.worker.global_worker
        w._call(w._gcs_request("kv_put", {
            "ns": JOBS_NS, "key": self.submission_id.encode(),
            "value": pickle.dumps({
                "submission_id": self.submission_id,
                "entrypoint": self.entrypoint,
                "status": self._status,
                "message": self._message,
                "logs": "".join(self._log_chunks[-2000:]),
                "update_ts": time.time(),
            })}))

    async def run(self):
        """Drive the subprocess to completion (fire-and-forget)."""
        import asyncio
        import os
        env = dict(os.environ)
        env.update({k: str(v) for k, v in self.env_vars.items()})
        # The job connects back to this cluster (the supervisor runs in a
        # worker whose env already carries the GCS address).
        self._status = JobStatus.RUNNING
        self._save()
        try:
            self.proc = await asyncio.create_subprocess_shell(
                self.entrypoint, env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT)
            assert self.proc.stdout is not None
            while True:
                line = await self.proc.stdout.readline()
                if not line:
                    break
                self._log_chunks.append(line.decode("utf-8", "replace"))
                if len(self._log_chunks) % 20 == 0:
                    self._save()
            rc = await self.proc.wait()
            if self._status == JobStatus.STOPPED:
                pass
            elif rc == 0:
                self._status = JobStatus.SUCCEEDED
            else:
                self._status = JobStatus.FAILED
                self._message = f"entrypoint exited with code {rc}"
        except Exception as e:
            self._status = JobStatus.FAILED
            self._message = repr(e)
        self._save()
        return self._status

    def stop(self):
        self._status = JobStatus.STOPPED
        if self.proc is not None and self.proc.returncode is None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass
        self._save()
        return True

    def ping(self):
        return True


class JobSubmissionClient:
    """Reference: python/ray/dashboard/modules/job/sdk.py — the same
    verbs, minus HTTP (the client talks straight to the cluster)."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, ignore_reinit_error=True)

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict] = None) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        env_vars = (runtime_env or {}).get("env_vars", {})
        sup_cls = ray_tpu.remote(JobSupervisor)
        sup = sup_cls.options(
            name=f"_rt_job:{submission_id}", lifetime="detached",
            num_cpus=0).remote(submission_id, entrypoint, env_vars)
        ray_tpu.get(sup.ping.remote(), timeout=60)
        sup.run.options(num_returns=0).remote()
        return submission_id

    def _record(self, submission_id: str) -> Optional[Dict]:
        w = ray_tpu._private.worker.global_worker
        blob = w._run(w._gcs_request("kv_get", {
            "ns": JOBS_NS, "key": submission_id.encode()}))["value"]
        return pickle.loads(blob) if blob else None

    def get_job_status(self, submission_id: str) -> str:
        rec = self._record(submission_id)
        if rec is None:
            raise KeyError(f"no such job {submission_id}")
        return rec["status"]

    def get_job_info(self, submission_id: str) -> Dict:
        rec = self._record(submission_id)
        if rec is None:
            raise KeyError(f"no such job {submission_id}")
        return rec

    def get_job_logs(self, submission_id: str) -> str:
        rec = self._record(submission_id)
        return rec["logs"] if rec else ""

    def stop_job(self, submission_id: str) -> bool:
        try:
            sup = ray_tpu.get_actor(f"_rt_job:{submission_id}")
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            return False

    def list_jobs(self) -> List[Dict]:
        w = ray_tpu._private.worker.global_worker
        keys = w._run(w._gcs_request(
            "kv_keys", {"ns": JOBS_NS, "prefix": b""}))["keys"]
        out = []
        for k in keys:
            rec = self._record(k.decode())
            if rec:
                out.append(rec)
        return out

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {submission_id} still "
                           f"{self.get_job_status(submission_id)}")
