"""Job submission: run a shell entrypoint as a supervised cluster job.

Reference: dashboard/modules/job/job_manager.py — JobManager (:320)
starting a JobSupervisor actor (:109) per job; the supervisor runs the
entrypoint as a subprocess, streams its output, and records status
transitions (PENDING -> RUNNING -> SUCCEEDED/FAILED/STOPPED) that clients
poll.  Status + logs live in the GCS KV so they survive the submitting
client.
"""

from __future__ import annotations

import pickle
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

JOBS_NS = "job_submissions"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobSupervisor:
    """Detached actor owning one job subprocess (reference:
    job_manager.py:109 JobSupervisor)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Optional[Dict[str, str]] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.env_vars = env_vars or {}
        self.proc = None
        self._log_chunks: List[str] = []
        self._total_chars = 0  # absolute log length incl. dropped prefix
        self._status = JobStatus.PENDING
        self._message = ""
        self._save()

    def _save(self):
        # Non-blocking KV push: run() executes ON the worker's event loop,
        # so a blocking _run here would deadlock the actor.
        w = ray_tpu._private.worker.global_worker
        w._call(w._gcs_request("kv_put", {
            "ns": JOBS_NS, "key": self.submission_id.encode(),
            "value": pickle.dumps({
                "submission_id": self.submission_id,
                "entrypoint": self.entrypoint,
                "status": self._status,
                "message": self._message,
                # Sliding window + the ABSOLUTE end offset, so tailers
                # can track progress even after the window slides.
                "logs": "".join(self._log_chunks[-2000:]),
                "logs_end": self._total_chars,
                "update_ts": time.time(),
            })}))

    async def run(self):
        """Drive the subprocess to completion (fire-and-forget)."""
        import asyncio
        import os
        env = dict(os.environ)
        env.update({k: str(v) for k, v in self.env_vars.items()})
        # The job connects back to this cluster (the supervisor runs in a
        # worker whose env already carries the GCS address).
        self._status = JobStatus.RUNNING
        self._save()
        try:
            self.proc = await asyncio.create_subprocess_shell(
                self.entrypoint, env=env,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT)
            assert self.proc.stdout is not None
            while True:
                line = await self.proc.stdout.readline()
                if not line:
                    break
                text = line.decode("utf-8", "replace")
                self._log_chunks.append(text)
                self._total_chars += len(text)
                if len(self._log_chunks) % 20 == 0:
                    self._save()
                    # Only the last 2000 chunks are ever persisted;
                    # trimming keeps the supervisor's memory bounded on
                    # chatty long-running jobs (lossless: _total_chars
                    # already carries the absolute offset).
                    if len(self._log_chunks) > 4000:
                        del self._log_chunks[:-2000]
            rc = await self.proc.wait()
            if self._status == JobStatus.STOPPED:
                pass
            elif rc == 0:
                self._status = JobStatus.SUCCEEDED
            else:
                self._status = JobStatus.FAILED
                self._message = f"entrypoint exited with code {rc}"
        except Exception as e:
            self._status = JobStatus.FAILED
            self._message = repr(e)
        self._save()
        return self._status

    def stop(self):
        self._status = JobStatus.STOPPED
        if self.proc is not None and self.proc.returncode is None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass
        self._save()
        return True

    def ping(self):
        return True


def _window_delta(rec: Dict, sent: int):
    """New log text since absolute offset `sent`, given a record with a
    sliding `logs` window ending at absolute offset `logs_end`."""
    logs = rec.get("logs", "")
    end = rec.get("logs_end", len(logs))
    if end <= sent:
        return "", sent
    start = end - len(logs)  # absolute offset of the window start
    return logs[max(0, sent - start):], end


class JobSubmissionClient:
    """Reference: python/ray/dashboard/modules/job/sdk.py — the same
    verbs.  An `http://host:port` address talks to the dashboard head's
    REST API from OUTSIDE the cluster (no driver connection at all);
    any other address connects directly like a driver."""

    def __init__(self, address: Optional[str] = None):
        self._http = None
        if address and address.startswith("http"):
            self._http = address.rstrip("/")
            return
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address, ignore_reinit_error=True)

    # ------------------------------------------------------- HTTP plane
    def _rest(self, method: str, path: str, body: Optional[Dict] = None):
        import json
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self._http}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                raw = r.read().decode()
        except urllib.error.HTTPError as e:
            raw = e.read().decode()
            try:
                err = json.loads(raw).get("error", raw)
            except Exception:
                err = raw
            if e.code == 404:
                raise KeyError(err) from None
            raise RuntimeError(f"job REST error: {err}") from None
        return json.loads(raw) if raw else None

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict] = None) -> str:
        if self._http:
            reply = self._rest("POST", "/api/jobs", {
                "entrypoint": entrypoint,
                "submission_id": submission_id,
                "runtime_env": runtime_env})
            return reply["submission_id"]
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        env_vars = (runtime_env or {}).get("env_vars", {})
        sup_cls = ray_tpu.remote(JobSupervisor)
        sup = sup_cls.options(
            name=f"_rt_job:{submission_id}", lifetime="detached",
            num_cpus=0).remote(submission_id, entrypoint, env_vars)
        ray_tpu.get(sup.ping.remote(), timeout=60)
        sup.run.options(num_returns=0).remote()
        return submission_id

    def _record(self, submission_id: str) -> Optional[Dict]:
        w = ray_tpu._private.worker.global_worker
        blob = w._run(w._gcs_request("kv_get", {
            "ns": JOBS_NS, "key": submission_id.encode()}))["value"]
        return pickle.loads(blob) if blob else None

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_info(self, submission_id: str) -> Dict:
        if self._http:
            return self._rest("GET", f"/api/jobs/{submission_id}")
        rec = self._record(submission_id)
        if rec is None:
            raise KeyError(f"no such job {submission_id}")
        return rec

    def get_job_logs(self, submission_id: str) -> str:
        if self._http:
            import urllib.request
            with urllib.request.urlopen(
                    f"{self._http}/api/jobs/{submission_id}/logs",
                    timeout=60) as r:
                return r.read().decode()
        rec = self._record(submission_id)
        return rec["logs"] if rec else ""

    def tail_job_logs(self, submission_id: str):
        """Yield log chunks until the job reaches a terminal state
        (HTTP mode streams the server's chunked ?follow=1 response)."""
        if self._http:
            import urllib.request
            import codecs
            decoder = codecs.getincrementaldecoder("utf-8")("replace")
            with urllib.request.urlopen(
                    f"{self._http}/api/jobs/{submission_id}/logs"
                    "?follow=1", timeout=3600) as r:
                while True:
                    # read1: return each transfer chunk as it arrives
                    # (read(n) would block accumulating n bytes,
                    # defeating the live tail); incremental decode keeps
                    # multibyte characters split across chunks intact.
                    chunk = r.read1(65536)
                    if not chunk:
                        tail = decoder.decode(b"", final=True)
                        if tail:
                            yield tail
                        return
                    text = decoder.decode(chunk)
                    if text:
                        yield text
        else:
            sent = 0
            while True:
                rec = self._record(submission_id)
                if rec is None:
                    return
                chunk, sent = _window_delta(rec, sent)
                if chunk:
                    yield chunk
                if rec.get("status") in JobStatus.TERMINAL:
                    return
                time.sleep(0.5)

    def stop_job(self, submission_id: str) -> bool:
        if self._http:
            reply = self._rest("POST",
                               f"/api/jobs/{submission_id}/stop")
            return bool(reply.get("stopped"))
        try:
            sup = ray_tpu.get_actor(f"_rt_job:{submission_id}")
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            return False

    def list_jobs(self) -> List[Dict]:
        if self._http:
            # /api/submissions: submission records only, matching the
            # direct-mode shape (/api/jobs also merges driver jobs).
            return self._rest("GET", "/api/submissions") or []
        w = ray_tpu._private.worker.global_worker
        keys = w._run(w._gcs_request(
            "kv_keys", {"ns": JOBS_NS, "prefix": b""}))["keys"]
        out = []
        for k in keys:
            rec = self._record(k.decode())
            if rec:
                out.append(rec)
        return out

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {submission_id} still "
                           f"{self.get_job_status(submission_id)}")
