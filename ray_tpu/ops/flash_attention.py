"""Flash attention: a Pallas TPU kernel for causal attention.

Re-design target: the O(S^2)-memory einsum attention is fine at seq 1024
but dead at 8k+ (VERDICT round 1).  This kernel streams KV blocks through
VMEM with online softmax, so memory is O(S * block) and the MXU sees
(block_q x head_dim) @ (head_dim x block_k) matmuls.  No reference
counterpart (the reference has no in-tree attention); algorithm follows
the public FlashAttention recurrence (m/l running max/sum).

Forward is the Pallas kernel; backward is a custom_vjp that recomputes
probabilities blockwise in plain XLA (same O(S^2) FLOPs as flash
backward, O(S*block) memory) — recompute-over-store is usually the right
trade on TPU where HBM bandwidth, not FLOPs, is the bottleneck.

Layout: [batch, heads, seq, head_dim]; head_dim must be a multiple of
128 (lane tiling), block sizes multiples of the sublane tile.
"""

from __future__ import annotations

import functools


import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _fit_block(seq_len: int, block: int) -> int:
    """Largest power-of-two block <= `block` dividing seq_len, >=128 —
    so a 512 default doesn't silently exclude sequences like 2816 that
    tile fine at 256 (the fallback einsum path costs O(S^2) HBM)."""
    b = block
    while b > 128 and seq_len % b != 0:
        b //= 2
    return b


def _dot_f32(a, b, trans_b=False):
    """MXU matmul keeping bf16 INPUTS at bf16 throughput with f32
    accumulation (upcasting the inputs first would run the MXU at the
    much slower fp32 rate — the single biggest kernel-efficiency lever)."""
    dims = (((1,), (1 if trans_b else 0,)), ((), ()))
    return lax.dot_general(a, b, dims,
                           preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_k,
                seq_len):
    """One (batch*head, q_block) program: stream KV blocks with the
    online-softmax recurrence."""
    q = q_ref[0]                                      # [bq, d] native dtype
    block_q = q.shape[0]
    i = pl.program_id(1)
    q_start = i * block_q

    m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    # Iotas hoisted out of the streaming loop (VPU passes are the
    # kernel's bottleneck, not the d=128 matmuls).  Unlike the backward
    # kernels, splitting this loop into unmasked/masked halves measured
    # SLOWER on v5e (17.4 vs 14.3 ms — the two dynamic-bound loops
    # defeat Mosaic's load pipelining), so the forward keeps one loop.
    q_iota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_iota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot_f32(q, k, trans_b=True) * scale       # [bq, bk] f32
        s = jnp.where(q_start + q_iota >= j * block_k + k_iota,
                      s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + _dot_f32(p.astype(v.dtype), v)
        return m_new, l_new, acc_new

    # KV blocks 0..floor(last_q_row / block_k) inclusive.
    n_kv = (q_start + block_q - 1) // block_k + 1
    m, l, acc = lax.fori_loop(0, n_kv, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    # lse is [bh, s, 1]: TPU lowering wants the last two block dims
    # (8,128)-tiled or full, which a [1, block_q] 2D block is not.
    lse_ref[0] = (m + jnp.log(l))[:, None]


def _flash_fwd(q, k, v, *, scale, block_q, block_k, interpret):
    b, h, s, d = q.shape
    block_q = _fit_block(s, block_q)
    block_k = _fit_block(s, block_k)
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    grid = (b * h, s // block_q)
    kernel = functools.partial(_fwd_kernel, scale=scale, block_k=block_k,
                               seq_len=s)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
    )(qf, kf, vf)
    return out.reshape(b, h, s, d), lse.reshape(b, h, s)


def _dq_kernel(q_ref, g_ref, lse_ref, delta_ref, k_ref, v_ref, dq_ref,
               *, scale, block_k):
    """dq for one q block: stream KV, recompute P from the saved lse
    (flash backward, dq half)."""
    q = q_ref[0]                                # [bq, d] native dtype
    gb = g_ref[0]
    lse = lse_ref[0].astype(jnp.float32)        # [bq, 1]
    delta = delta_ref[0].astype(jnp.float32)    # [bq, 1]
    block_q = q.shape[0]
    i = pl.program_id(1)
    q_start = i * block_q

    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    q_iota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_iota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def step(j, acc, masked):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :]
        vb = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = _dot_f32(q, kb, trans_b=True) * scale
        p = jnp.exp(s - lse)
        if masked:
            p = jnp.where(q_start + q_iota >= j * block_k + k_iota,
                          p, 0.0)
        dp = _dot_f32(gb, vb, trans_b=True)
        ds = (p * (dp - delta)).astype(kb.dtype)
        return acc + _dot_f32(ds, kb)

    n_full = q_start // block_k
    n_kv = (q_start + block_q - 1) // block_k + 1
    acc = lax.fori_loop(0, n_full,
                        lambda j, a: step(j, a, masked=False), acc0)
    acc = lax.fori_loop(n_full, n_kv,
                        lambda j, a: step(j, a, masked=True), acc)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _dkdv_kernel(k_ref, v_ref, q_ref, g_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, *, scale, block_q, n_q_blocks):
    """dk/dv for one kv block: stream the q blocks that can attend to it
    (flash backward, dk/dv half).  Requires block_q == block_k."""
    kb = k_ref[0]                               # [bk, d] native dtype
    vb = v_ref[0]
    block_k = kb.shape[0]
    j = pl.program_id(1)
    k_start = j * block_k

    dk0 = jnp.zeros((block_k, kb.shape[1]), jnp.float32)
    dv0 = jnp.zeros((block_k, vb.shape[1]), jnp.float32)
    q_iota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_iota = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def step(i, carry, masked):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :]
        gb = g_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        s = _dot_f32(qb, kb, trans_b=True) * scale
        p = jnp.exp(s - lse)
        if masked:
            p = jnp.where(i * block_q + q_iota >= k_start + k_iota,
                          p, 0.0)
        pb = p.astype(gb.dtype)
        dv = dv + lax.dot_general(pb, gb, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        ds = (p * (_dot_f32(gb, vb, trans_b=True) - delta)).astype(qb.dtype)
        dk = dk + lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    # Only q blocks at/after this kv block attend to it (causal); with
    # block_q == block_k exactly the first of them straddles the diagonal.
    carry = step(j, (dk0, dv0), masked=True)
    dk, dv = lax.fori_loop(j + 1, n_q_blocks,
                           lambda i, c: step(i, c, masked=False), carry)
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, *, scale, block, interpret):
    block = _fit_block(q.shape[2], block)
    b, h, s, d = q.shape
    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * h, s, d)
    vf = v.reshape(b * h, s, d)
    gf = g.reshape(b * h, s, d)
    # delta_i = g_i . out_i, the rowwise correction of flash backward.
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    delta = delta.reshape(b * h, s, 1)
    lse3 = lse.reshape(b * h, s, 1)
    n_blocks = s // block

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, block_k=block),
        grid=(b * h, n_blocks),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block, 1), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block, 1), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
    )(qf, gf, lse3, delta, kf, vf)

    dk, dv = pl.pallas_call(
        functools.partial(_dkdv_kernel, scale=scale, block_q=block,
                          n_q_blocks=n_blocks),
        grid=(b * h, n_blocks),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, s, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, s, 1), lambda bh, j: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, block, d), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), v.dtype),
        ],
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024),
    )(kf, vf, qf, gf, lse3, delta)

    return (dq.reshape(b, h, s, d), dk.reshape(b, h, s, d),
            dv.reshape(b, h, s, d))


def _blockwise_bwd(q, k, v, out, lse, g, *, scale, block_q):
    """Flash backward as blockwise XLA: recompute P per q-block from the
    saved logsumexp, accumulate dq/dk/dv with a scan over q blocks."""
    b, h, s, d = q.shape
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    of = out.astype(jnp.float32)
    # delta_i = sum_j g_ij * out_ij (rowwise), per flash backward.
    delta = (gf * of).sum(-1)                         # [b,h,s]

    n_blocks = s // block_q
    k_ids = jnp.arange(s)

    def body(carry, idx):
        dk, dv = carry
        sl = idx * block_q
        qb = lax.dynamic_slice_in_dim(qf, sl, block_q, axis=2)
        gb = lax.dynamic_slice_in_dim(gf, sl, block_q, axis=2)
        lseb = lax.dynamic_slice_in_dim(lse, sl, block_q, axis=2)
        deltab = lax.dynamic_slice_in_dim(delta, sl, block_q, axis=2)
        # s_ij = scale * q_i . k_j ; ds/dq = scale*k, ds/dk = scale*q.
        sbl = jnp.einsum("bhqd,bhkd->bhqk", qb, kf) * scale
        q_ids = sl + jnp.arange(block_q)
        mask = q_ids[:, None] >= k_ids[None, :]
        pb = jnp.where(mask, jnp.exp(sbl - lseb[..., None]), 0.0)
        dpb = jnp.einsum("bhqd,bhkd->bhqk", gb, vf)
        dsb = pb * (dpb - deltab[..., None])
        dqb = jnp.einsum("bhqk,bhkd->bhqd", dsb, kf) * scale
        dk = dk + jnp.einsum("bhqk,bhqd->bhkd", dsb, qb) * scale
        dv = dv + jnp.einsum("bhqk,bhqd->bhkd", pb, gb)
        return (dk, dv), dqb

    (dk, dv), dq_blocks = lax.scan(
        body, (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        jnp.arange(n_blocks))
    # dq_blocks: [n_blocks, b, h, block_q, d] -> [b, h, s, d]
    dq = jnp.moveaxis(dq_blocks, 0, 2).reshape(b, h, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, scale=None, block_q=DEFAULT_BLOCK_Q,
                    block_k=DEFAULT_BLOCK_K, interpret=False):
    """Causal flash attention. q,k,v: [batch, heads, seq, head_dim]."""
    out, _ = _flash_fwd(q, k, v, scale=scale or q.shape[-1] ** -0.5,
                        block_q=block_q, block_k=block_k,
                        interpret=interpret)
    return out


def _vjp_fwd(q, k, v, scale, block_q, block_k, interpret):
    scale = scale or q.shape[-1] ** -0.5
    out, lse = _flash_fwd(q, k, v, scale=scale, block_q=block_q,
                          block_k=block_k, interpret=interpret)
    return out, (q, k, v, out, lse)


def _vjp_bwd(scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    scale = scale or q.shape[-1] ** -0.5
    if block_q == block_k:
        # Pallas backward: P recomputed per block pair from the saved
        # lse, no O(block*S) XLA intermediates in HBM.
        return _flash_bwd_pallas(q, k, v, out, lse, g, scale=scale,
                                 block=block_q, interpret=interpret)
    return _blockwise_bwd(q, k, v, out, lse, g, scale=scale,
                          block_q=block_q)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def supports(seq_len: int, head_dim: int, block_q: int = DEFAULT_BLOCK_Q,
             block_k: int = DEFAULT_BLOCK_K) -> bool:
    """Shape gate: lane tiling wants head_dim % 128 == 0; the block size
    auto-fits downward (to >=128) for sequences the default block doesn't
    divide, so only seq % 128 must hold."""
    bq = _fit_block(seq_len, block_q)
    bk = _fit_block(seq_len, block_k)
    return (head_dim % 128 == 0 and seq_len % bq == 0
            and seq_len % bk == 0 and seq_len >= bq)
