"""LLaMA-family decoder: RoPE + GQA + SwiGLU + RMSNorm, SPMD-sharded.

The reference frameworks stop at gang-scheduling (SURVEY.md §2.4); model
families here are first-class and share one mesh vocabulary (see
models/gpt.py for the flagship that adds pp/ep).  This family covers the
modern-decoder recipe:

  RoPE    rotary position embedding — no learned position table; under
          sp the global position offset comes from the shard's ring index
  GQA     grouped-query attention: n_kv_heads < n_heads; K/V heads are
          sharded over tp alongside Q heads and broadcast to the query
          groups at use (kv projections and cache stay Hkv-sized)
  SwiGLU  silu(x W_g) * (x W_u) W_d, hidden sharded over tp
  RMSNorm no-mean normalization (fp32 accumulation)

Mesh axes: dp / fsdp (ZeRO-style just-in-time gather) / tp (heads +
ffn hidden + vocab) / sp (ring attention).  `mesh=None` runs the same
math on one device.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models.gpt import (
    BATCH_AXES,
    _all_gather,
    _axis_index,
    _psum,
    _rmsnorm,
    _shard_map,
)
from ray_tpu.parallel.ring_attention import (
    _ring_attention_sharded,
    reference_attention,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_kv_heads: int = 4
    n_layers: int = 8
    d_ff: int = 1536            # SwiGLU hidden width
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash: bool = True

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def group_size(self) -> int:
        assert self.n_heads % self.n_kv_heads == 0
        return self.n_heads // self.n_kv_heads


# ---------------------------------------------------------------------------
# Parameters


def init_params(cfg: LlamaConfig, key) -> dict:
    k = iter(jax.random.split(key, 16))
    L, D, H, Hk, Dh, F = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                          cfg.n_kv_heads, cfg.head_dim, cfg.d_ff)
    s = 0.02
    so = s / np.sqrt(2 * L)

    def nrm(key, shape, scale):
        return (scale * jax.random.normal(key, shape)).astype(jnp.float32)

    blocks = {
        "ln1": jnp.ones((L, D), jnp.float32),
        "wq": nrm(next(k), (L, D, H, Dh), s),
        "wkv": nrm(next(k), (L, D, 2, Hk, Dh), s),
        "wo": nrm(next(k), (L, H, Dh, D), so),
        "ln2": jnp.ones((L, D), jnp.float32),
        "w_gate": nrm(next(k), (L, D, F), s),
        "w_up": nrm(next(k), (L, D, F), s),
        "w_down": nrm(next(k), (L, F, D), so),
    }
    return {
        "wte": nrm(next(k), (cfg.vocab_size, D), s),
        "blocks": blocks,
        "ln_f": jnp.ones((D,), jnp.float32),
        "wlm": nrm(next(k), (D, cfg.vocab_size), s),
    }


def param_specs(cfg: LlamaConfig) -> dict:
    """Q and KV heads over tp (needs n_kv_heads % tp == 0); model dim of
    the big matrices over fsdp, gathered just-in-time in the block."""
    blocks = {
        "ln1": P(None, None),
        "wq": P(None, "fsdp", "tp", None),
        "wkv": P(None, "fsdp", None, "tp", None),
        "wo": P(None, "tp", None, "fsdp"),
        "ln2": P(None, None),
        "w_gate": P(None, "fsdp", "tp"),
        "w_up": P(None, "fsdp", "tp"),
        "w_down": P(None, "tp", "fsdp"),
    }
    return {
        "wte": P("tp", None),
        "blocks": blocks,
        "ln_f": P(None),
        "wlm": P(None, "tp"),
    }


# ---------------------------------------------------------------------------
# RoPE


def _rope(x, t0, theta: float):
    """x: [b, t, h, d] -> rotated (rotate-half form).  t0 = global
    position of this shard's first token (nonzero under sp)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = (t0 + jnp.arange(t, dtype=jnp.float32))[:, None] * freqs[None, :]
    cos = jnp.cos(pos)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(pos)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


# ---------------------------------------------------------------------------
# Block body (inside shard_map, or plain when mesh=None)


def _attention(x, p, cfg: LlamaConfig, active):
    dt = cfg.dtype
    wq = _all_gather(p["wq"], "fsdp", 0, active).astype(dt)
    wkv = _all_gather(p["wkv"], "fsdp", 0, active).astype(dt)
    q = jnp.einsum("btd,dhk->bthk", x, wq)
    kv = jnp.einsum("btd,dchk->btchk", x, wkv)
    kk, v = kv[:, :, 0], kv[:, :, 1]

    t_local = x.shape[1]
    t0 = (_axis_index("sp", active) * t_local).astype(jnp.float32) \
        if "sp" in active else jnp.float32(0)
    q = _rope(q, t0, cfg.rope_theta)
    kk = _rope(kk, t0, cfg.rope_theta)

    # GQA: each kv head serves a group of rep = H/Hkv query heads
    # (tp-invariant since both are sharded over tp).  Under sp the ring
    # rotates K/V at Hkv size — the wire and cache keep GQA's saving —
    # and each step broadcasts the received block locally; off-ring the
    # broadcast happens once up front.
    rep = q.shape[2] // kk.shape[2]
    scale = cfg.head_dim ** -0.5
    if "sp" in active:
        out = _ring_attention_sharded(q, kk, v, "sp", causal=True,
                                      scale=scale, kv_repeat=rep)
    else:
        if rep > 1:
            kk = jnp.repeat(kk, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        out = None
        if cfg.use_flash and jax.default_backend() == "tpu":
            from ray_tpu.ops import flash_attention as fa
            t = q.shape[1]
            if t >= 2048 and fa.supports(t, cfg.head_dim):
                out = fa.flash_attention(
                    q.transpose(0, 2, 1, 3), kk.transpose(0, 2, 1, 3),
                    v.transpose(0, 2, 1, 3), scale).transpose(0, 2, 1, 3)
        if out is None:
            out = reference_attention(q, kk, v, causal=True, scale=scale)
    wo = _all_gather(p["wo"], "fsdp", 2, active).astype(dt)
    y = jnp.einsum("bthk,hkd->btd", out, wo)
    return _psum(y, ("tp",), active)


def _swiglu_ffn(x, p, cfg: LlamaConfig, active):
    dt = cfg.dtype
    wg = _all_gather(p["w_gate"], "fsdp", 0, active).astype(dt)
    wu = _all_gather(p["w_up"], "fsdp", 0, active).astype(dt)
    wd = _all_gather(p["w_down"], "fsdp", 1, active).astype(dt)
    h = jax.nn.silu(jnp.einsum("btd,df->btf", x, wg)) \
        * jnp.einsum("btd,df->btf", x, wu)
    y = jnp.einsum("btf,fd->btd", h, wd)
    return _psum(y, ("tp",), active)


def _blocks_body(blocks, x, cfg: LlamaConfig, active):
    def layer(x, lp):
        x = x + _attention(_rmsnorm(x, lp["ln1"]), lp, cfg, active)
        x = x + _swiglu_ffn(_rmsnorm(x, lp["ln2"]), lp, cfg, active)
        return x, None
    if cfg.remat:
        layer = jax.checkpoint(layer)
    x, _ = lax.scan(layer, x, blocks)
    return x


# ---------------------------------------------------------------------------
# Forward / loss / train step (mirrors models/gpt.py)


def forward(params: dict, tokens, cfg: LlamaConfig, mesh=None):
    """tokens: [B, T] int32 -> logits [B, T, vocab] (fp32)."""
    if tokens.shape[1] > cfg.max_seq:
        raise ValueError(f"sequence length {tokens.shape[1]} exceeds "
                         f"max_seq={cfg.max_seq}")
    dt = cfg.dtype
    x = jnp.take(params["wte"], tokens, axis=0).astype(dt)

    if mesh is None:
        x = _blocks_body(params["blocks"], x, cfg, frozenset())
    else:
        active = frozenset(mesh.axis_names)
        x_spec = P(BATCH_AXES, "sp", None)
        x = lax.with_sharding_constraint(x, NamedSharding(mesh, x_spec))
        body = functools.partial(_blocks_body, cfg=cfg, active=active)
        x = _shard_map(body, mesh,
                       (param_specs(cfg)["blocks"], x_spec),
                       x_spec)(params["blocks"], x)

    x = _rmsnorm(x, params["ln_f"])
    logits = jnp.einsum("btd,dv->btv", x.astype(jnp.float32),
                        params["wlm"].astype(jnp.float32))
    if mesh is not None:
        logits = lax.with_sharding_constraint(
            logits, NamedSharding(mesh, P(BATCH_AXES, "sp", "tp")))
    return logits


def loss_fn(params, tokens, cfg: LlamaConfig, mesh=None):
    import optax
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, cfg, mesh)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return loss.mean()


def make_train_state(cfg: LlamaConfig, key, mesh=None, optimizer=None,
                     learning_rate: float = 3e-4):
    import optax
    optimizer = optimizer or optax.adamw(learning_rate)
    params = init_params(cfg, key)
    if mesh is not None:
        specs = param_specs(cfg)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs)
    opt_state = optimizer.init(params)
    return ({"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}, optimizer)


def train_step(state, tokens, cfg: LlamaConfig, mesh=None, optimizer=None):
    import optax
    optimizer = optimizer or optax.adamw(3e-4)
    loss, grads = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg, mesh))(state["params"])
    updates, new_opt = optimizer.update(grads, state["opt_state"],
                                        state["params"])
    new_params = optax.apply_updates(state["params"], updates)
    return ({"params": new_params, "opt_state": new_opt,
             "step": state["step"] + 1}, {"loss": loss})


def make_train_step(cfg: LlamaConfig, mesh=None, optimizer=None,
                    learning_rate: float = 3e-4, donate: bool = True):
    import optax
    optimizer = optimizer or optax.adamw(learning_rate)
    fn = functools.partial(train_step, cfg=cfg, mesh=mesh,
                           optimizer=optimizer)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
