"""Vision Transformer: second model family on the same mesh machinery.

No reference counterpart (the reference ships no in-tree models); this
exists to show the parallelism substrate generalizes beyond the decoder:
the encoder reuses gpt's block stack (bidirectional attention via
GPTConfig(causal=False)) with the same dp/fsdp/tp/pp shardings, so ViT
training scales with the identical mesh recipe as the flagship GPT.

Layout: images [B, H, W, C] -> patches [B, N, P*P*C] -> transformer ->
mean-pooled classification head.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt
from ray_tpu.models.gpt import BATCH_AXES, _rmsnorm, _shard_map


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    channels: int = 3
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 1024
    num_classes: int = 10
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def num_patches(self) -> int:
        assert self.image_size % self.patch_size == 0
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.channels

    def gpt_cfg(self) -> gpt.GPTConfig:
        """The encoder core, expressed as a bidirectional GPT stack."""
        return gpt.GPTConfig(
            vocab_size=8, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, d_ff=self.d_ff,
            max_seq=self.num_patches, dtype=self.dtype,
            remat=self.remat, causal=False, use_flash=False)


def init_params(cfg: ViTConfig, key) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    core = gpt.init_params(cfg.gpt_cfg(), k1)
    s = 0.02
    return {
        "patch_embed": (s * jax.random.normal(
            k2, (cfg.patch_dim, cfg.d_model))).astype(jnp.float32),
        "pos": (s * jax.random.normal(
            k3, (cfg.num_patches, cfg.d_model))).astype(jnp.float32),
        "blocks": core["blocks"],
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
        "head": (s * jax.random.normal(
            k4, (cfg.d_model, cfg.num_classes))).astype(jnp.float32),
    }


def param_specs(cfg: ViTConfig) -> dict:
    core = gpt.param_specs(cfg.gpt_cfg())
    return {
        "patch_embed": P(None, None),
        "pos": P(None, None),
        "blocks": core["blocks"],
        "ln_f": P(None),
        "head": P(None, "tp"),
    }


def _patchify(images, cfg: ViTConfig):
    """[B, H, W, C] -> [B, N, P*P*C]."""
    b, h, w, c = images.shape
    p = cfg.patch_size
    x = images.reshape(b, h // p, p, w // p, p, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, (h // p) * (w // p), p * p * c)


def forward(params: dict, images, cfg: ViTConfig, mesh=None):
    """images [B, H, W, C] float -> logits [B, num_classes] (fp32)."""
    gcfg = cfg.gpt_cfg()
    x = _patchify(images.astype(jnp.float32), cfg)
    x = (x @ params["patch_embed"] + params["pos"]).astype(cfg.dtype)

    if mesh is None:
        x = gpt._blocks_body(params["blocks"], x, gcfg, frozenset(), {})
    else:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        # Size-1 axes contribute nothing (their collectives are no-ops)
        # and sp/ep size-1 must not trip the causal-only/expert guards.
        active = frozenset(n for n in mesh.axis_names if sizes[n] > 1)
        x_spec = P(BATCH_AXES, None, None)
        x = lax.with_sharding_constraint(x, NamedSharding(mesh, x_spec))
        body = functools.partial(gpt._blocks_body, cfg=gcfg,
                                 active=active, sizes=sizes)
        x = _shard_map(body, mesh,
                       (gpt._block_in_specs(gcfg), x_spec),
                       x_spec)(params["blocks"], x)

    x = _rmsnorm(x, params["ln_f"]).astype(jnp.float32)
    pooled = x.mean(axis=1)
    logits = pooled @ params["head"].astype(jnp.float32)
    if mesh is not None:
        logits = lax.with_sharding_constraint(
            logits, NamedSharding(mesh, P(BATCH_AXES, "tp")))
    return logits


def loss_fn(params, images, labels, cfg: ViTConfig, mesh=None):
    import optax
    logits = forward(params, images, cfg, mesh)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels).mean()


def make_train_state(cfg: ViTConfig, key, mesh=None, optimizer=None,
                     learning_rate: float = 1e-3):
    import optax
    optimizer = optimizer or optax.adamw(learning_rate)
    params = init_params(cfg, key)
    if mesh is not None:
        specs = param_specs(cfg)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs)
    opt_state = optimizer.init(params)
    return ({"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}, optimizer)


def train_step(state, images, labels, cfg: ViTConfig, mesh=None,
               optimizer=None):
    import optax
    optimizer = optimizer or optax.adamw(1e-3)
    loss, grads = jax.value_and_grad(
        lambda p: loss_fn(p, images, labels, cfg, mesh))(state["params"])
    updates, new_opt = optimizer.update(grads, state["opt_state"],
                                        state["params"])
    return ({"params": optax.apply_updates(state["params"], updates),
             "opt_state": new_opt, "step": state["step"] + 1},
            {"loss": loss})


def make_train_step(cfg: ViTConfig, mesh=None, optimizer=None,
                    learning_rate: float = 1e-3, donate: bool = True):
    import optax
    optimizer = optimizer or optax.adamw(learning_rate)
    fn = functools.partial(train_step, cfg=cfg, mesh=mesh,
                           optimizer=optimizer)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
