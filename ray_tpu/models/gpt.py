"""Flagship decoder-only transformer, SPMD-sharded over every mesh axis.

One train step composes the full parallelism inventory (SURVEY.md §2.4 —
all absent in the reference, first-class here):

  dp / fsdp  batch sharding (+ ZeRO-style parameter sharding: params are
             stored fsdp-sharded and all-gathered just-in-time inside the
             block body; the shard_map transpose turns the gather into a
             reduce-scatter of the gradients)
  tp         megatron-style: attention heads and ffn hidden sharded; one
             psum per residual branch rides ICI
  pp         GPipe pipeline expressed as a collective program: stages are
             the pp-shards of the stacked layer parameters, microbatch
             activations hop stages via lax.ppermute
  sp         ring attention (parallel/ring_attention.py): K/V blocks rotate
             the sp ring with online-softmax accumulation — exact attention
             with O(T/sp) memory
  ep         MoE ffn with experts sharded over ep, combined with a single
             psum over (ep, tp)

The whole block stack runs inside ONE shard_map island over the full mesh;
embedding/unembedding stay at the GSPMD level (vocab sharded over tp) so
XLA inserts the input/output collectives.  bfloat16 compute on the MXU,
fp32 params/optimizer/logits.  `mesh=None` runs the identical math on a
single device (collectives become no-ops) — that is the driver's
single-chip `entry()` path.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel.ring_attention import (
    _ring_attention_sharded,
    reference_attention,
)

BATCH_AXES = ("dp", "fsdp")


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    d_ff: int = 2048
    max_seq: int = 2048
    n_experts: int = 0          # 0 = dense ffn; >0 = MoE sharded over ep
    capacity_factor: float = 2.0
    num_microbatches: int = 1   # pipeline microbatches (used when pp > 1)
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full": checkpoint the whole layer — minimum HBM, the backward
    # recomputes the layer forward (including the flash kernel).  Set
    # remat_save_attn=True to additionally pin the attention output
    # across the checkpoint (skips the O(T^2) re-run for O(B*T*D) HBM
    # per layer).
    # "ffn": checkpoint only the ffn branch; all attention residuals
    # (q/k/v/out/lse) are stored.  More HBM than "full".
    remat_mode: str = "full"
    # With remat_mode="full": additionally pin the attention output
    # across the layer checkpoint (skips the O(T^2) forward re-run in
    # the backward at O(B*T*D) HBM per layer).  Off by default — on
    # 16G-HBM v5e the lost batch size outweighs the saved recompute.
    remat_save_attn: bool = False
    # Pallas flash attention for long sequences (TPU only; falls back to
    # the einsum reference off-TPU or on non-tiling shapes).
    use_flash: bool = True
    # Blockwise LM-head loss: compute the [chunk, vocab] logits + CE a
    # token-chunk at a time (checkpointed, so backward recomputes one
    # chunk's logits) instead of materializing the full [B*T, vocab]
    # f32 logits tensor — at B=8 T=4096 V=32k that tensor alone is
    # 4.2 GB of HBM.  0 = off.  Single-chip path only; the sharded path
    # keeps logits materialized under its tp sharding.
    loss_chunk: int = 0
    # False = bidirectional attention (encoder models, e.g. models/vit).
    causal: bool = True

    def __post_init__(self):
        if self.remat_mode not in ("full", "ffn"):
            raise ValueError(f"remat_mode must be 'full' or 'ffn', "
                             f"got {self.remat_mode!r}")

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# Parameters


def init_params(cfg: GPTConfig, key) -> dict:
    """fp32 parameter pytree; block leaves stacked over layers (leading L)."""
    k = iter(jax.random.split(key, 16))
    L, D, H, Dh, F = (cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.head_dim,
                      cfg.d_ff)
    s = 0.02
    so = s / np.sqrt(2 * L)  # residual-output scaling (GPT-2 style)

    def nrm(key, shape, scale):
        return (scale * jax.random.normal(key, shape)).astype(jnp.float32)

    blocks = {
        "ln1": jnp.ones((L, D), jnp.float32),
        "wqkv": nrm(next(k), (L, D, 3, H, Dh), s),
        "wo": nrm(next(k), (L, H, Dh, D), so),
        "ln2": jnp.ones((L, D), jnp.float32),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        blocks["gate"] = nrm(next(k), (L, D, E), s)
        blocks["w_in"] = nrm(next(k), (L, E, D, F), s)
        blocks["w_out"] = nrm(next(k), (L, E, F, D), so)
    else:
        blocks["w1"] = nrm(next(k), (L, D, F), s)
        blocks["w2"] = nrm(next(k), (L, F, D), so)
    return {
        "wte": nrm(next(k), (cfg.vocab_size, D), s),
        "wpe": nrm(next(k), (cfg.max_seq, D), s),
        "blocks": blocks,
        "ln_f": jnp.ones((D,), jnp.float32),
        "wlm": nrm(next(k), (D, cfg.vocab_size), s),
    }


def param_specs(cfg: GPTConfig) -> dict:
    """PartitionSpec pytree mirroring init_params.

    Layer stack over pp; heads/ffn-hidden/vocab over tp; model dim of the
    big matrices over fsdp (gathered just-in-time in the block body)."""
    blocks = {
        "ln1": P("pp", None),
        "wqkv": P("pp", "fsdp", None, "tp", None),
        "wo": P("pp", "tp", None, "fsdp"),
        "ln2": P("pp", None),
    }
    if cfg.n_experts:
        blocks["gate"] = P("pp", None, None)
        blocks["w_in"] = P("pp", "ep", None, "tp")
        blocks["w_out"] = P("pp", "ep", "tp", None)
    else:
        blocks["w1"] = P("pp", "fsdp", "tp")
        blocks["w2"] = P("pp", "tp", "fsdp")
    return {
        "wte": P("tp", None),
        "wpe": P(None, None),
        "blocks": blocks,
        "ln_f": P(None),
        "wlm": P(None, "tp"),
    }


def _block_in_specs(cfg: GPTConfig) -> dict:
    return param_specs(cfg)["blocks"]


# ---------------------------------------------------------------------------
# Collective helpers: no-ops when running without a mesh (single device).


def _psum(x, names, active):
    names = tuple(n for n in names if n in active)
    return lax.psum(x, names) if names else x


def _axis_index(name, active):
    return lax.axis_index(name) if name in active else 0


def _all_gather(x, name, axis, active):
    if name in active:
        return lax.all_gather(x, name, axis=axis, tiled=True)
    return x


# ---------------------------------------------------------------------------
# Block body (runs inside shard_map over the full mesh, or plain when
# mesh=None).  All shapes below are per-shard.


def _rmsnorm(x, scale):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _attention(x, p, cfg, active, sizes):
    """x: [b, t_local, D].  Heads sharded over tp; sequence over sp."""
    dt = cfg.dtype
    wqkv = _all_gather(p["wqkv"], "fsdp", 0, active).astype(dt)
    qkv = jnp.einsum("btd,dchk->btchk", x, wqkv)  # c=3, h local heads
    q, kk, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scale = cfg.head_dim ** -0.5
    if "sp" in active:
        if not cfg.causal:
            raise NotImplementedError(
                "sequence-parallel (sp) attention is causal-only")
        out = _ring_attention_sharded(q, kk, v, "sp", causal=True,
                                      scale=scale)
    else:
        out = None
        if cfg.use_flash and jax.default_backend() == "tpu":
            from ray_tpu.ops import flash_attention as fa
            t = q.shape[1]
            # Below ~2k XLA's fused einsum attention wins (measured on
            # v5e: 52% vs 50% MFU at 1024); flash pays off where the
            # O(S^2) score tensor stops fitting the fusion budget.
            if cfg.causal and t >= 2048 and fa.supports(t, cfg.head_dim):
                # [b,t,h,k] -> [b,h,t,k] for the kernel and back.
                out = fa.flash_attention(
                    q.transpose(0, 2, 1, 3), kk.transpose(0, 2, 1, 3),
                    v.transpose(0, 2, 1, 3), scale).transpose(0, 2, 1, 3)
        if out is None:
            out = reference_attention(q, kk, v, causal=cfg.causal,
                                      scale=scale)
    # Name the attention output so the remat policy can pin it in HBM:
    # under "full" remat everything else in the layer is recomputed, but
    # re-running the O(T^2) attention forward would be the one recompute
    # that actually costs (the rest is cheap matmuls/elementwise).
    from jax.ad_checkpoint import checkpoint_name
    out = checkpoint_name(out, "attn_out")
    wo = _all_gather(p["wo"], "fsdp", 2, active).astype(dt)
    y = jnp.einsum("bthk,hkd->btd", out, wo)
    return _psum(y, ("tp",), active)


def _dense_ffn(x, p, cfg, active):
    dt = cfg.dtype
    w1 = _all_gather(p["w1"], "fsdp", 0, active).astype(dt)
    w2 = _all_gather(p["w2"], "fsdp", 1, active).astype(dt)
    h = jax.nn.gelu(jnp.einsum("btd,df->btf", x, w1))
    y = jnp.einsum("btf,fd->btd", h, w2)
    return _psum(y, ("tp",), active)


def _moe_ffn(x, p, cfg, active, sizes):
    """Experts sharded over ep, expert-hidden over tp (parallel/moe.py
    pattern, extended with the tp reduction).  Routing is computed
    redundantly on every (ep, tp) shard; each shard runs only its local
    experts' capacity buckets as one batched einsum (MXU-friendly)."""
    from ray_tpu.parallel.moe import top1_dispatch
    dt = cfg.dtype
    ep_size = sizes.get("ep", 1)
    my = _axis_index("ep", active)
    b, t, d = x.shape
    xf = x.reshape(b * t, d)
    dispatch, combine = top1_dispatch(
        xf, p["gate"], p["w_in"].shape[0], my, ep_size,
        cfg.capacity_factor, dtype=dt)
    w_in = p["w_in"].astype(dt)
    w_out = p["w_out"].astype(dt)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xf)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, w_in))
    out = jnp.einsum("ecf,efd->ecd", h, w_out)
    y = jnp.einsum("nec,ecd->nd", combine, out)
    return _psum(y, ("ep", "tp"), active).reshape(b, t, d)


def _make_layer_fn(cfg: GPTConfig, active, sizes):
    def ffn_branch(x, lp):
        h = _rmsnorm(x, lp["ln2"])
        if cfg.n_experts:
            y = _moe_ffn(h, lp, cfg, active, sizes)
        else:
            y = _dense_ffn(h, lp, cfg, active)
        return x + y

    if cfg.remat and cfg.remat_mode == "ffn":
        # With the flash kernel, attention stays un-rematted (its
        # residuals — incl. the flash lse — are O(B*T*D) and stored, so
        # the O(T^2) forward never re-runs); the ffn branch and the
        # pre-attention norm recompute (the norm's checkpoint avoids
        # storing stacked fp32 upcasts of x).
        ffn_ckpt = jax.checkpoint(ffn_branch)
        norm_ckpt = jax.checkpoint(_rmsnorm)

        def attn_branch(x, lp):
            return x + _attention(norm_ckpt(x, lp["ln1"]), lp, cfg,
                                  active, sizes)

        if not (cfg.use_flash and jax.default_backend() == "tpu"):
            # The einsum attention would store O(T^2) probabilities per
            # layer if left un-rematted — checkpoint it too (two-segment
            # remat instead of whole-layer).
            attn_branch = jax.checkpoint(attn_branch)

        def layer(x, lp):
            return ffn_ckpt(attn_branch(x, lp), lp), None
        return layer

    def layer(x, lp):
        a = _attention(_rmsnorm(x, lp["ln1"]), lp, cfg, active, sizes)
        x = x + a
        return ffn_branch(x, lp), None
    if cfg.remat:
        # Measured on v5e at seq 4096: pinning attn_out in HBM
        # (save_only_these_names) forces batch 8 -> 7 and nets LESS
        # throughput (45.6% vs 52.6% MFU), so the recompute-everything
        # policy stays the default; flip remat_save_attn on chips with
        # more HBM headroom.
        policy = (jax.checkpoint_policies.save_only_these_names("attn_out")
                  if cfg.remat_save_attn else None)
        layer = jax.checkpoint(layer, policy=policy)
    return layer


def _stage_fn(blocks, x, cfg, active, sizes):
    """Scan this shard's layer stack (the full stack when pp=1)."""
    x, _ = lax.scan(_make_layer_fn(cfg, active, sizes), x, blocks)
    return x


def _blocks_body(blocks, x, cfg: GPTConfig, active, sizes):
    """x: [b_local, t_local, D] per-shard activations.

    pp=1: plain layer scan.  pp>1: GPipe-as-collectives — microbatches
    stream through the pp stages via ppermute (parallel/pipeline.py
    pattern, inlined so the stage body can itself use sp/tp/ep
    collectives)."""
    pp = sizes.get("pp", 1)
    if pp == 1:
        return _stage_fn(blocks, x, cfg, active, sizes)

    M = cfg.num_microbatches
    b = x.shape[0]
    assert b % M == 0, f"local batch {b} not divisible by microbatches {M}"
    x_mb = x.reshape(M, b // M, *x.shape[1:])
    s_idx = _axis_index("pp", active)
    ticks = M + pp - 1
    perm = [(i, i + 1) for i in range(pp - 1)]

    stream0 = jnp.zeros_like(x_mb[0])
    outputs0 = jnp.zeros_like(x_mb)

    def tick(carry, t):
        stream, outputs = carry
        mb_idx = jnp.clip(t - s_idx, 0, M - 1)
        inp = jnp.where(s_idx == 0, x_mb[jnp.clip(t, 0, M - 1)], stream)
        out = _stage_fn(blocks, inp, cfg, active, sizes)
        valid = (t - s_idx >= 0) & (t - s_idx < M)
        rec = valid & (s_idx == pp - 1)
        outputs = jnp.where(rec, outputs.at[mb_idx].set(out), outputs)
        stream_next = lax.ppermute(out, "pp", perm)
        return (stream_next, outputs), None

    (_, outputs), _ = lax.scan(tick, (stream0, outputs0), jnp.arange(ticks))
    # Only the last stage holds real outputs; replicate across pp (callers
    # need the activations everywhere for the unembed + loss).
    outputs = jnp.where(s_idx == pp - 1, outputs, jnp.zeros_like(outputs))
    outputs = _psum(outputs, ("pp",), active)
    return outputs.reshape(b, *x.shape[1:])


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map with replication checking off (the body mixes psum /
    ppermute / at-set updates whose varying-axis types the checker can't
    always infer), across jax API versions."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (TypeError, AttributeError):
        pass
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
    except (TypeError, AttributeError):
        from jax.experimental.shard_map import shard_map as sm
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


# ---------------------------------------------------------------------------
# Forward / loss / train step


def hidden_states(params: dict, tokens, cfg: GPTConfig, mesh=None):
    """tokens: [B, T] int32 -> final-norm hidden states [B, T, d]."""
    B, T = tokens.shape
    dt = cfg.dtype
    x = jnp.take(params["wte"], tokens, axis=0)
    x = (x + params["wpe"][:T]).astype(dt)

    if mesh is None:
        x = _blocks_body(params["blocks"], x, cfg, frozenset(), {})
    else:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        active = frozenset(mesh.axis_names)
        x_spec = P(BATCH_AXES, "sp", None)
        x = lax.with_sharding_constraint(x, NamedSharding(mesh, x_spec))
        body = functools.partial(_blocks_body, cfg=cfg, active=active,
                                 sizes=sizes)
        x = _shard_map(body, mesh, (_block_in_specs(cfg), x_spec),
                       x_spec)(params["blocks"], x)

    return _rmsnorm(x, params["ln_f"])


def forward(params: dict, tokens, cfg: GPTConfig, mesh=None):
    """tokens: [B, T] int32 -> logits [B, T, vocab] (fp32)."""
    x = hidden_states(params, tokens, cfg, mesh)
    # bf16 operands, f32 accumulation: upcasting the INPUTS would push
    # the lm-head matmul off the fast MXU path (and the [B,T,vocab]
    # logits are produced in f32 either way for a stable softmax).
    logits = jnp.einsum("btd,dv->btv", x.astype(cfg.dtype),
                        params["wlm"].astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    if mesh is not None:
        logits = lax.with_sharding_constraint(
            logits, NamedSharding(mesh, P(BATCH_AXES, "sp", "tp")))
    return logits


def loss_fn(params, tokens, cfg: GPTConfig, mesh=None):
    """Next-token cross entropy; tokens [B, T+1]."""
    import optax
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    chunk = cfg.loss_chunk
    B, T = inputs.shape
    if chunk and mesh is None and (B * T) % chunk != 0:
        # Requested chunk doesn't divide the token count: round DOWN to
        # the largest divisor <= chunk rather than silently falling back
        # to the full-logits path the option exists to avoid.
        chunk = next(c for c in range(min(chunk, B * T), 0, -1)
                     if (B * T) % c == 0)
    if chunk and mesh is None:
        # Blockwise LM head: one token-chunk's [chunk, vocab] logits
        # live at a time; jax.checkpoint recomputes them in backward
        # (~3% extra FLOPs) instead of keeping the full f32 logits
        # resident — the freed HBM buys batch/remat headroom.
        x = hidden_states(params, inputs, cfg, mesh)
        xf = x.reshape(B * T, -1).astype(cfg.dtype)
        tf = targets.reshape(B * T)
        wlm = params["wlm"].astype(cfg.dtype)

        @jax.checkpoint
        def _chunk_ce(xc, tc):
            logits = jnp.einsum("nd,dv->nv", xc, wlm,
                                preferred_element_type=jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tc)

        n = (B * T) // chunk
        losses = lax.map(lambda a: _chunk_ce(*a),
                         (xf.reshape(n, chunk, -1),
                          tf.reshape(n, chunk)))
        return losses.mean()
    logits = forward(params, inputs, cfg, mesh)
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return loss.mean()


def make_train_state(cfg: GPTConfig, key, mesh=None, optimizer=None,
                     learning_rate: float = 3e-4):
    """Init params (+adamw state), placed according to param_specs."""
    import optax
    optimizer = optimizer or optax.adamw(learning_rate)
    params = init_params(cfg, key)
    if mesh is not None:
        specs = param_specs(cfg)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            params, specs)
    opt_state = optimizer.init(params)
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    return state, optimizer


def train_step(state, tokens, cfg: GPTConfig, mesh=None, optimizer=None):
    """One SGD step (not jitted — wrap with make_train_step)."""
    import optax
    optimizer = optimizer or optax.adamw(3e-4)
    loss, grads = jax.value_and_grad(
        lambda p: loss_fn(p, tokens, cfg, mesh))(state["params"])
    updates, new_opt = optimizer.update(grads, state["opt_state"],
                                        state["params"])
    new_params = optax.apply_updates(state["params"], updates)
    return ({"params": new_params, "opt_state": new_opt,
             "step": state["step"] + 1}, {"loss": loss})


def make_train_step(cfg: GPTConfig, mesh=None, optimizer=None,
                    learning_rate: float = 3e-4, donate: bool = True):
    import optax
    optimizer = optimizer or optax.adamw(learning_rate)
    fn = functools.partial(train_step, cfg=cfg, mesh=mesh,
                           optimizer=optimizer)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())
