"""Autoregressive decoding with a KV cache for the flagship models.

The reference delegates inference to user frameworks (vLLM/torch); here
the model layer is ours, so serving-side decode is part of the
framework.  TPU-native design constraints drive the shape of this
module:

  * static shapes everywhere — the cache is a fixed [L, B, max_seq,
    Hkv, Dh] buffer updated with lax.dynamic_update_slice, and the
    per-step attention masks positions > pos instead of slicing, so one
    XLA compilation serves the whole generation;
  * the decode loop is a lax.scan (one dispatch for the whole
    generation, not one per token — dispatch latency dominates
    single-token steps through a tunneled chip);
  * GQA caches stay at Hkv size (the memory saving is the point of
    GQA); query-head groups are expanded at the attention einsum.

Single-device path (serve replicas own one chip); the training-side
mesh machinery (models/gpt.py) is unchanged.  Supports GPT (learned
positions, fused QKV) and LLaMA (RoPE, GQA, SwiGLU).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import llama as llama_mod
from ray_tpu.models.gpt import _rmsnorm


# ---------------------------------------------------------------------------
# Arch adapters: how each model family embeds tokens and builds q/k/v/ffn.


def _is_llama(cfg) -> bool:
    return isinstance(cfg, llama_mod.LlamaConfig)


def _kv_heads(cfg) -> int:
    return cfg.n_kv_heads if _is_llama(cfg) else cfg.n_heads


def _rope_at(x, positions, theta: float):
    """RoPE with PER-ROW positions [B, t] (left-padded batches put the
    same logical position at different columns per row; llama.py's
    _rope takes one scalar offset for the whole batch)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = positions.astype(jnp.float32)[:, :, None] * freqs[None, None]
    cos = jnp.cos(pos)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(pos)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _embed(params, tokens, positions, cfg):
    """tokens [B, t] at per-row logical positions [B, t]."""
    x = jnp.take(params["wte"], tokens, axis=0)
    if not _is_llama(cfg):
        x = x + jnp.take(params["wpe"], positions, axis=0)
    return x.astype(cfg.dtype)


def _qkv(lp, h, positions, cfg):
    """h [B, t, D] -> q [B,t,H,Dh], k/v [B,t,Hkv,Dh] (RoPE applied at
    per-row logical positions for llama)."""
    dt = cfg.dtype
    if _is_llama(cfg):
        q = jnp.einsum("btd,dhk->bthk", h, lp["wq"].astype(dt))
        kv = jnp.einsum("btd,dchk->btchk", h, lp["wkv"].astype(dt))
        k, v = kv[:, :, 0], kv[:, :, 1]
        q = _rope_at(q, positions, cfg.rope_theta)
        k = _rope_at(k, positions, cfg.rope_theta)
        return q, k, v
    qkv = jnp.einsum("btd,dchk->btchk", h, lp["wqkv"].astype(dt))
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def _ffn(lp, x, cfg):
    dt = cfg.dtype
    h = _rmsnorm(x, lp["ln2"])
    if _is_llama(cfg):
        g = jax.nn.silu(jnp.einsum("btd,df->btf", h,
                                   lp["w_gate"].astype(dt)))
        u = jnp.einsum("btd,df->btf", h, lp["w_up"].astype(dt))
        return x + jnp.einsum("btf,fd->btd", g * u,
                              lp["w_down"].astype(dt))
    hh = jax.nn.gelu(jnp.einsum("btd,df->btf", h, lp["w1"].astype(dt)))
    return x + jnp.einsum("btf,fd->btd", hh, lp["w2"].astype(dt))


def _attn_out(lp, out, cfg):
    return jnp.einsum("bthk,hkd->btd", out, lp["wo"].astype(cfg.dtype))


def _final_logits(params, x, cfg):
    x = _rmsnorm(x, params["ln_f"])
    return jnp.einsum("btd,dv->btv", x.astype(cfg.dtype),
                      params["wlm"].astype(cfg.dtype),
                      preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Cache


def init_cache(cfg, batch: int, max_seq: Optional[int] = None) -> Dict:
    """Fixed-shape KV cache: k/v [L, B, S, Hkv, Dh] in cfg.dtype."""
    S = max_seq or cfg.max_seq
    shape = (cfg.n_layers, batch, S, _kv_heads(cfg), cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


@functools.partial(jax.jit, donate_argnums=(0,))
def reset_cache_slot(cache: Dict, slot) -> Dict:
    """Zero one batch row of the cache (slot recycling: when the
    continuous-batching engine evicts a finished request, its slot is
    wiped so the next occupant starts from the documented all-zeros
    state).  `slot` is a traced scalar — one compilation serves every
    slot index."""
    L, B, S, H, D = cache["k"].shape
    z = jnp.zeros((L, 1, S, H, D), cache["k"].dtype)
    return {"k": lax.dynamic_update_slice(
                cache["k"], z, (0, slot, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                cache["v"], z, (0, slot, 0, 0, 0))}


@functools.partial(jax.jit, donate_argnums=(0,))
def insert_cache_slot(cache: Dict, row_cache: Dict, slot) -> Dict:
    """Copy batch row 0 of `row_cache` (a batch-1 cache filled by
    prefill/chunk_step) into batch row `slot` of `cache` — continuous-
    batching admission: a request prefilled off to the side joins the
    decode batch without touching any other row.  Sequence widths must
    match; `slot` is a traced scalar (single compilation)."""
    return {"k": lax.dynamic_update_slice(
                cache["k"], row_cache["k"][:, :1], (0, slot, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                cache["v"], row_cache["v"][:, :1], (0, slot, 0, 0, 0))}


def init_paged_cache(cfg, num_pages: int, page_size: int) -> Dict:
    """Paged KV pool: k/v [L, P, page_size, Hkv, Dh] in cfg.dtype.

    Rows of a batch don't own contiguous cache rows here — each row owns
    a BLOCK TABLE of page ids, and attention gathers its keys/values
    through the table (vLLM's PagedAttention layout, expressed in the
    same masked static-shape style as the contiguous cache: gather to a
    fixed virtual width, mask columns past the row's position).  The
    serve engine reserves page 0 as a trash page for inactive rows'
    writes; this initializer doesn't care."""
    shape = (cfg.n_layers, num_pages, page_size, _kv_heads(cfg),
             cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype),
            "v": jnp.zeros(shape, cfg.dtype)}


@functools.partial(jax.jit, donate_argnums=(0,))
def paged_write_pages(cache: Dict, page_ids, k_pages, v_pages) -> Dict:
    """Splice imported K/V pages into the pool: k_pages/v_pages
    [n, L, page_size, Hkv, Dh] (page-major — each page's bytes travel
    the wire as one contiguous buffer) land at pool rows `page_ids`
    [n].  One scatter per cache tensor, cache donated: a KV migration
    commits between decode ticks as a single dispatch, never a
    reallocation or a tick stall."""
    return {"k": cache["k"].at[:, page_ids].set(
                jnp.moveaxis(k_pages, 0, 1).astype(cache["k"].dtype)),
            "v": cache["v"].at[:, page_ids].set(
                jnp.moveaxis(v_pages, 0, 1).astype(cache["v"].dtype))}


@jax.jit
def paged_read_pages(cache: Dict, page_ids) -> Tuple[Any, Any]:
    """Gather pool rows `page_ids` [n] as page-major
    [n, L, page_size, Hkv, Dh] K and V stacks — the export half of a KV
    migration (device_get of the result is the only host copy)."""
    return (jnp.moveaxis(cache["k"][:, page_ids], 0, 1),
            jnp.moveaxis(cache["v"][:, page_ids], 0, 1))


def paged_read_pages_host(cache: Dict, page_ids) -> Tuple[Any, Any]:
    """paged_read_pages + the host landing: contiguous page-major numpy
    K/V stacks, ready to frame byte-for-byte (tier demotion, migration
    export).  One fused device gather however many pages ride along —
    the demotion sweeper batches a whole sweep into one call, and the
    promote/demote paths share this copy discipline so their bytes can
    never diverge from what the wire path ships."""
    import numpy as np
    k, v = paged_read_pages(
        cache, jnp.asarray(np.asarray(page_ids, np.int32)))
    return np.ascontiguousarray(k), np.ascontiguousarray(v)


def paged_chunk_step(params: Dict, tokens, pos, cache: Dict,
                     block_tables, cfg, pad_lo=None
                     ) -> Tuple[Any, Dict]:
    """Decode a chunk of t tokens [B, t] through a PAGED cache.

    `block_tables` [B, nblk] maps each row's virtual cache columns to
    pages of the pool: virtual column c lives at
    (block_tables[b, c // page], c % page).  `pos` is a scalar (one
    shared start column — single-row prefill) or a [B] vector (each row
    chunked at its own depth — the fused speculative verify).  Row b's
    chunk K/V is scattered at columns pos[b]..pos[b]+t-1 through its
    table, then attention gathers the row's pages back to a
    [B, nblk*page] virtual buffer and masks columns > pos[b]+i exactly
    like the contiguous chunk_step — unmasked columns hold bit-identical
    values to a contiguous cache, so paging is invisible to results.

    Callers must keep pos+t within nblk*page (writes past the table
    would clip into the last block).  Returns (logits [B, t, V] fp32,
    updated cache)."""
    B, t = tokens.shape
    psz = cache["k"].shape[2]
    nblk = block_tables.shape[1]
    S = nblk * psz
    pos = jnp.asarray(pos, jnp.int32)
    offs = jnp.arange(t)
    cols = jnp.broadcast_to(jnp.reshape(pos, (-1, 1)) + offs[None, :],
                            (B, t))                    # global columns
    if pad_lo is None:
        pad_lo = jnp.zeros((B,), jnp.int32)
    positions = cols - pad_lo[:, None]
    x = _embed(params, tokens, positions, cfg)
    w_pages = jnp.take_along_axis(block_tables, cols // psz, axis=1)
    w_offs = cols % psz
    kcols = jnp.arange(S)
    mask = (kcols[None, None, :] <= cols[:, :, None]) \
        & (kcols[None, None, :] >= pad_lo[:, None, None])

    def layer(x, inputs):
        lp, ck_l, cv_l = inputs                  # [P, psz, Hkv, Dh]
        h = _rmsnorm(x, lp["ln1"])
        q, k, v = _qkv(lp, h, positions, cfg)
        ck_l = ck_l.at[w_pages, w_offs].set(k.astype(ck_l.dtype))
        cv_l = cv_l.at[w_pages, w_offs].set(v.astype(cv_l.dtype))
        Hkv, Dh = ck_l.shape[2], ck_l.shape[3]
        ck = ck_l[block_tables].reshape(B, S, Hkv, Dh)
        cv = cv_l[block_tables].reshape(B, S, Hkv, Dh)
        rep = q.shape[2] // Hkv
        qg = q.reshape(B, t, Hkv, rep, Dh)
        scores = jnp.einsum("bqgrk,bsgk->bgrqs", qg.astype(jnp.float32),
                            ck.astype(jnp.float32)) \
            * cfg.head_dim ** -0.5
        scores = jnp.where(mask[:, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bgrqs,bsgk->bqgrk", probs.astype(cv.dtype), cv)
        out = out.reshape(B, t, q.shape[2], Dh)
        x = x + _attn_out(lp, out, cfg)
        x = _ffn(lp, x, cfg)
        return x, (ck_l, cv_l)

    x, (ck, cv) = lax.scan(layer, x,
                           (params["blocks"], cache["k"], cache["v"]))
    return _final_logits(params, x, cfg), {"k": ck, "v": cv}


def paged_decode_step(params: Dict, token, pos, cache: Dict,
                      block_tables, cfg, pad_lo=None
                      ) -> Tuple[Any, Dict]:
    """One token [B] at per-row cache columns pos [B] through a paged
    cache — the continuous-batching tick.  A t=1 paged_chunk_step (the
    SAME kernel the speculative verify runs, so a speculation-free tick
    and a verify tick can never drift numerically)."""
    logits, cache = paged_chunk_step(params, token[:, None], pos, cache,
                                     block_tables, cfg, pad_lo=pad_lo)
    return logits[:, 0], cache


def _cached_attention(q, ck, cv, pos, pad_lo, cfg):
    """q [B,1,H,Dh] against the cache's first pos+1 positions (static
    shape: positions > pos are masked, not sliced; columns < pad_lo[b]
    are left-padding and masked too).  `pos` is a scalar (whole batch at
    one column — the lockstep generate() path) or a [B] vector (each
    row at its own depth — the continuous-batching engine).  GQA stays
    at Hkv width: q is folded to [B,1,Hkv,rep,Dh] and contracted
    against the Hkv-sized cache — no repeated cache copy per step."""
    B, S, Hkv, Dh = ck.shape
    rep = q.shape[2] // Hkv
    qg = q.reshape(B, 1, Hkv, rep, Dh)
    scale = cfg.head_dim ** -0.5
    scores = jnp.einsum("bqgrk,bsgk->bgrqs", qg.astype(jnp.float32),
                        ck.astype(jnp.float32)) * scale
    cols = jnp.arange(S)
    pos_col = jnp.reshape(jnp.asarray(pos), (-1, 1))  # [1,1] or [B,1]
    mask = (cols[None, :] <= pos_col) \
        & (cols[None, :] >= pad_lo[:, None])
    scores = jnp.where(mask[:, None, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bgrqs,bsgk->bqgrk", probs.astype(cv.dtype), cv)
    return out.reshape(B, 1, Hkv * rep, Dh)


# ---------------------------------------------------------------------------
# Prefill + single-step decode


def prefill(params: Dict, tokens, cfg, cache: Dict, prompt_lens=None
            ) -> Tuple[Any, Dict]:
    """Run the prompt [B, T] through the model, filling cache[:, :, :T].

    With `prompt_lens` [B], rows are treated as LEFT-padded to width T:
    row b's real tokens occupy columns T-len..T-1, get logical
    positions 0..len-1, and its padding columns are masked out of every
    attention (they contribute nothing to any real token).

    Returns (logits [B, T, V] fp32, cache)."""
    B, T = tokens.shape
    cols = jnp.arange(T)
    if prompt_lens is None:
        pad_lo = jnp.zeros((B,), jnp.int32)       # first real column
        positions = jnp.broadcast_to(cols, (B, T))
    else:
        pad_lo = (T - jnp.asarray(prompt_lens, jnp.int32))
        positions = jnp.maximum(cols[None, :] - pad_lo[:, None], 0)
    x = _embed(params, tokens, positions, cfg)
    # causal AND not-padding: [B, q, k].  Pad queries additionally
    # attend to THEMSELVES: a query with zero valid keys softmaxes an
    # all--inf row into NaNs, and those NaNs reach real columns through
    # 0-weight * NaN-value products in the next layer's value einsum —
    # self-attention keeps pad lanes finite (their outputs are garbage
    # but masked out of every real token's view).
    mask = (cols[None, None, :] <= cols[None, :, None]) \
        & ((cols[None, None, :] >= pad_lo[:, None, None])
           | (cols[None, None, :] == cols[None, :, None]))

    def layer(x, inputs):
        lp, ck_l, cv_l = inputs
        h = _rmsnorm(x, lp["ln1"])
        q, k, v = _qkv(lp, h, positions, cfg)
        ck_l = lax.dynamic_update_slice(
            ck_l, k.astype(ck_l.dtype), (0, 0, 0, 0))
        cv_l = lax.dynamic_update_slice(
            cv_l, v.astype(cv_l.dtype), (0, 0, 0, 0))
        rep = q.shape[2] // k.shape[2]
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        scores = jnp.einsum("bqhk,bshk->bhqs", q.astype(jnp.float32),
                            k.astype(jnp.float32)) \
            * cfg.head_dim ** -0.5
        scores = jnp.where(mask[:, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqs,bshk->bqhk", probs.astype(v.dtype), v)
        x = x + _attn_out(lp, out, cfg)
        x = _ffn(lp, x, cfg)
        return x, (ck_l, cv_l)

    x, (ck, cv) = lax.scan(layer, x,
                           (params["blocks"], cache["k"], cache["v"]))
    return _final_logits(params, x, cfg), {"k": ck, "v": cv}


def decode_step(params: Dict, token, pos, cache: Dict, cfg,
                pad_lo=None) -> Tuple[Any, Dict]:
    """One token [B] at cache column pos -> (logits [B, V], updated
    cache).  `pos` is a scalar int (every row writes the same column —
    whole-batch generate()) or a [B] int vector (each row writes its OWN
    column — continuous batching, where slots are mid-generation at
    different depths; writes become a per-row scatter).  pad_lo [B]
    marks each row's first real cache column (0 without left-padding).
    Jit once per shape; every step reuses the compilation."""
    B = token.shape[0]
    pos = jnp.asarray(pos, jnp.int32)
    per_row = pos.ndim == 1
    if pad_lo is None:
        pad_lo = jnp.zeros((B,), jnp.int32)
    positions = (pos - pad_lo)[:, None]  # logical position per row
    rows = jnp.arange(B)

    x = _embed(params, token[:, None], positions, cfg)

    def layer(x, inputs):
        lp, ck_l, cv_l = inputs
        h = _rmsnorm(x, lp["ln1"])
        q, k, v = _qkv(lp, h, positions, cfg)
        if per_row:
            ck_l = ck_l.at[rows, pos].set(k[:, 0].astype(ck_l.dtype))
            cv_l = cv_l.at[rows, pos].set(v[:, 0].astype(cv_l.dtype))
        else:
            ck_l = lax.dynamic_update_slice(
                ck_l, k.astype(ck_l.dtype), (0, pos, 0, 0))
            cv_l = lax.dynamic_update_slice(
                cv_l, v.astype(cv_l.dtype), (0, pos, 0, 0))
        out = _cached_attention(q, ck_l, cv_l, pos, pad_lo, cfg)
        x = x + _attn_out(lp, out, cfg)
        x = _ffn(lp, x, cfg)
        return x, (ck_l, cv_l)

    x, (ck, cv) = lax.scan(layer, x,
                           (params["blocks"], cache["k"], cache["v"]))
    return _final_logits(params, x, cfg)[:, 0], {"k": ck, "v": cv}


def chunk_step(params: Dict, tokens, pos, cache: Dict, cfg,
               pad_lo=None) -> Tuple[Any, Dict]:
    """Decode a CHUNK of t tokens [B, t] starting at cache column pos
    (scalar) in one forward: used by speculative verification, where
    the draft's t tokens are scored together instead of one dispatch
    per token.  Returns (logits [B, t, V], cache with the chunk's K/V
    written at pos..pos+t-1)."""
    B, t = tokens.shape
    if pad_lo is None:
        pad_lo = jnp.zeros((B,), jnp.int32)
    offs = jnp.arange(t)
    positions = (pos + offs)[None, :] - pad_lo[:, None]
    x = _embed(params, tokens, positions, cfg)

    def layer(x, inputs):
        lp, ck_l, cv_l = inputs
        h = _rmsnorm(x, lp["ln1"])
        q, k, v = _qkv(lp, h, positions, cfg)
        ck_l = lax.dynamic_update_slice(
            ck_l, k.astype(ck_l.dtype), (0, pos, 0, 0))
        cv_l = lax.dynamic_update_slice(
            cv_l, v.astype(cv_l.dtype), (0, pos, 0, 0))
        # q col i (global pos+i) sees cache cols in [pad_lo, pos+i].
        S = ck_l.shape[1]
        Hkv = ck_l.shape[2]
        rep = q.shape[2] // Hkv
        qg = q.reshape(B, t, Hkv, rep, -1)
        scores = jnp.einsum("bqgrk,bsgk->bgrqs",
                            qg.astype(jnp.float32),
                            ck_l.astype(jnp.float32)) \
            * cfg.head_dim ** -0.5
        cols = jnp.arange(S)
        mask = (cols[None, None, :] <= (pos + offs)[None, :, None]) \
            & (cols[None, None, :] >= pad_lo[:, None, None])
        scores = jnp.where(mask[:, None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bgrqs,bsgk->bqgrk", probs.astype(cv_l.dtype),
                         cv_l)
        out = out.reshape(B, t, q.shape[2], -1)
        x = x + _attn_out(lp, out, cfg)
        x = _ffn(lp, x, cfg)
        return x, (ck_l, cv_l)

    x, (ck, cv) = lax.scan(layer, x,
                           (params["blocks"], cache["k"], cache["v"]))
    return _final_logits(params, x, cfg), {"k": ck, "v": cv}


# ---------------------------------------------------------------------------
# Generation


def _sample(logits, key, temperature: float, top_k: int):
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                             "temperature", "top_k"))
def _generate_jit(params, prompt, prompt_lens, cfg, max_new_tokens,
                  temperature, top_k, key):
    B, T = prompt.shape
    S = T + max_new_tokens
    cache = init_cache(cfg, B, max_seq=S)
    pad_lo = T - prompt_lens
    logits, cache = prefill(params, prompt, cfg, cache,
                            prompt_lens=prompt_lens)
    key, sub = jax.random.split(key)
    first = _sample(logits[:, -1], sub, temperature, top_k)

    def step(carry, _):
        token, pos, cache, key = carry
        logits, cache = decode_step(params, token, pos, cache, cfg,
                                    pad_lo=pad_lo)
        key, sub = jax.random.split(key)
        nxt = _sample(logits, sub, temperature, top_k)
        return (nxt, pos + 1, cache, key), token

    (last, _, _, _), toks = lax.scan(
        step, (first, jnp.int32(T), cache, key), None,
        length=max_new_tokens - 1)
    toks = jnp.moveaxis(toks, 0, 1)  # [B, max_new-1]
    return jnp.concatenate([toks, last[:, None]], axis=1)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens",
                                             "ngram", "k"))
def _generate_speculative_jit(params, prompt, prompt_lens, cfg,
                              max_new_tokens, ngram, k):
    """Greedy prompt-lookup speculative decoding (the draft model is
    the context itself: the k tokens that followed the most recent
    earlier occurrence of the current n-gram).  One chunk_step scores
    all k drafts + the bonus token per iteration; the acceptance rule
    (keep the longest prefix where draft == argmax) makes the output
    IDENTICAL to plain greedy decode — speculation changes dispatch
    count, never results.  Stale cache/buffer entries past the accept
    point sit at columns > pos and are invisible to the masked
    attention until overwritten."""
    B, T = prompt.shape
    S = T + max_new_tokens + k + 1  # slack for the last chunk's writes
    cache = init_cache(cfg, B, max_seq=S)
    pad_lo = T - prompt_lens
    logits, cache = prefill(params, prompt, cfg, cache,
                            prompt_lens=prompt_lens)
    first = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
    buf = jnp.concatenate(
        [prompt.astype(jnp.int32),
         jnp.zeros((B, S - T), jnp.int32)], axis=1)
    buf = lax.dynamic_update_slice(buf, first[:, None], (0, T))
    end = T + max_new_tokens

    def lookup(buf, pos):
        """Per row: tokens following the latest earlier occurrence of
        buf[pos-ngram+1 .. pos] (the n-gram ENDING at the pending
        token); zeros when no match."""
        key = lax.dynamic_slice(
            buf, (0, pos - (ngram - 1)), (B, ngram))
        # windows starting at j cover buf[j .. j+ngram-1]
        idx = jnp.arange(S - ngram + 1)[:, None] + jnp.arange(ngram)
        wins = buf[:, idx]                       # [B, S-n+1, n]
        hit = jnp.all(wins == key[:, None, :], axis=-1)
        starts = jnp.arange(S - ngram + 1)
        # candidate must END before pos and leave room to read k tokens
        ok = (starts + ngram - 1 < pos) & hit
        j = jnp.max(jnp.where(ok, starts, -1), axis=1)  # latest match
        has = j >= 0
        draft_start = jnp.where(has, j + ngram, 0)
        gather = draft_start[:, None] + jnp.arange(k)[None]
        draft = jnp.take_along_axis(buf, gather, axis=1)
        return jnp.where(has[:, None], draft, 0)

    def cond(carry):
        _, pos, _, _, _ = carry
        return pos < end

    def body(carry):
        token, pos, cache, buf, iters = carry
        draft = lookup(buf, pos)                       # [B, k]
        chunk = jnp.concatenate([token[:, None], draft], axis=1)
        logits, cache = chunk_step(params, chunk, pos, cache, cfg,
                                   pad_lo=pad_lo)
        preds = jnp.argmax(logits, -1).astype(jnp.int32)  # [B, k+1]
        # accepted[i] = all drafts before i matched the model
        match = preds[:, :-1] == draft                 # [B, k]
        acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
        m = jnp.sum(acc, axis=1)                       # 0..k per row
        # lockstep batch: advance by the batch MINIMUM (every row's
        # cache write head must stay identical for the shared pos)
        m_min = jnp.minimum(jnp.min(m), end - 1 - pos)
        # outputs: accepted drafts then the bonus prediction at m_min
        out_chunk = jnp.concatenate([draft, jnp.zeros((B, 1),
                                                      jnp.int32)], 1)
        bonus = jnp.take_along_axis(preds, m_min[None].repeat(B)[:,
                                                                 None],
                                    axis=1)[:, 0]
        out_chunk = jnp.where(
            jnp.arange(k + 1)[None, :] == m_min, bonus[:, None],
            out_chunk)
        keep = jnp.arange(k + 1)[None, :] <= m_min
        cur = lax.dynamic_slice(buf, (0, pos + 1), (B, k + 1))
        buf = lax.dynamic_update_slice(
            buf, jnp.where(keep, out_chunk, cur), (0, pos + 1))
        token = bonus
        return token, pos + m_min + 1, cache, buf, iters + 1

    token0 = first
    carry = (token0, jnp.int32(T), cache, buf, jnp.int32(0))
    _, _, _, buf, iters = lax.while_loop(cond, body, carry)
    return lax.dynamic_slice(buf, (0, T), (B, max_new_tokens)), iters


def generate(params: Dict, prompt, cfg, *, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0,
             key=None, eos_token: Optional[int] = None,
             prompt_lens=None, speculate_ngram: int = 0,
             speculate_k: int = 0, return_stats: bool = False):
    """prompt [B, T] -> generated tokens [B, max_new_tokens].

    temperature 0 = greedy; top_k > 0 restricts sampling.  One jit
    compilation per (shape, cfg, knobs); the whole loop runs on device
    as a single dispatch.  Mixed-length batches: LEFT-pad each row to a
    common width and pass `prompt_lens` [B] — pad columns are masked
    out of attention and logical positions start at each row's first
    real token, so results match per-row unbatched generation.

    Return type depends on eos_token: WITHOUT it, a [B, max_new_tokens]
    array; WITH it, a ragged LIST of per-row 1-D arrays, each truncated
    before its first EOS (truncation is host-side so the device loop
    stays static-shape)."""
    if getattr(cfg, "n_experts", 0):
        raise NotImplementedError("decode supports dense models (MoE "
                                  "routing caches are not implemented)")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, "
                         f"got {max_new_tokens}")
    B, T = prompt.shape
    S = T + max_new_tokens + (speculate_k + 1 if speculate_k else 0)
    if not _is_llama(cfg) and S > cfg.max_seq:
        raise ValueError(f"prompt + max_new_tokens (+ speculative "
                         f"slack) = {S} exceeds max_seq={cfg.max_seq} "
                         f"(learned positions)")
    key = key if key is not None else jax.random.PRNGKey(0)
    if prompt_lens is None:
        prompt_lens = jnp.full((B,), T, jnp.int32)
    else:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    stats = None
    if speculate_k > 0:
        # Prompt-lookup speculation: greedy-only (sampled acceptance
        # needs rejection sampling; out of scope) — the output is
        # bit-identical to plain greedy decode, only faster.
        if temperature > 0.0:
            raise ValueError("speculative decoding is greedy-only "
                             "(temperature must be 0)")
        if speculate_ngram < 1:
            raise ValueError("speculate_ngram must be >= 1 when "
                             "speculate_k is set")
        if T < speculate_ngram:
            raise ValueError(f"prompt length {T} shorter than "
                             f"speculate_ngram={speculate_ngram}")
        out, iters = _generate_speculative_jit(
            params, jnp.asarray(prompt, jnp.int32), prompt_lens, cfg,
            max_new_tokens, int(speculate_ngram), int(speculate_k))
        stats = {"verify_steps": int(iters),
                 "tokens_per_step": max_new_tokens / max(1, int(iters))}
    else:
        out = _generate_jit(params, jnp.asarray(prompt, jnp.int32),
                            prompt_lens, cfg, max_new_tokens,
                            float(temperature), int(top_k), key)
    if eos_token is not None:
        import numpy as np
        arr = np.asarray(out)
        # one vectorized argmax over the hit mask, not an O(B) host
        # loop of np.where: rows without an EOS keep their full width.
        hit = arr == eos_token
        cut = np.where(hit.any(axis=1), hit.argmax(axis=1), arr.shape[1])
        out = [row[:n] for row, n in zip(arr, cut)]
    return (out, stats) if return_stats else out
