"""Model zoo for the TPU-native framework.

The flagship is a decoder-only transformer (models/gpt.py) whose single
train step composes every first-class parallelism axis (dp/fsdp/tp/pp/sp/ep
— SURVEY.md §2.4: all absent from the reference, first-class here).
"""

from ray_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    init_params,
    forward,
    loss_fn,
    train_step,
    make_train_state,
    param_specs,
)
from ray_tpu.models.llama import LlamaConfig  # noqa: F401
from ray_tpu.models import decode  # noqa: F401
