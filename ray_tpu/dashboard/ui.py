"""The dashboard's web UI: one dependency-free HTML page.

Reference role: the dashboard React client (dashboard/client) — scoped to
a single self-contained page that polls the head's JSON endpoints
(/api/nodes, /api/actors, /api/jobs, /api/serve, /api/events) and renders
cluster resources, per-node hardware utilization, actors, jobs, serve
applications, and recent events.  No build step, no bundler: the head
serves this string at "/ui".
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f6f7f9; color: #1a1c20; }
  header { background: #14202e; color: #fff; padding: 10px 20px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 17px; margin: 0; font-weight: 600; }
  header span { color: #9fb2c8; font-size: 12px; }
  main { padding: 16px 20px; max-width: 1200px; margin: 0 auto; }
  section { background: #fff; border: 1px solid #e3e6ea;
            border-radius: 8px; margin-bottom: 16px; padding: 12px 16px; }
  h2 { font-size: 13px; text-transform: uppercase; letter-spacing: .06em;
       color: #5a6472; margin: 0 0 8px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th { text-align: left; color: #5a6472; font-weight: 600;
       border-bottom: 1px solid #e3e6ea; padding: 4px 10px 4px 0; }
  td { border-bottom: 1px solid #f0f2f4; padding: 4px 10px 4px 0;
       font-variant-numeric: tabular-nums; }
  .pill { display: inline-block; padding: 1px 8px; border-radius: 10px;
          font-size: 12px; }
  .ALIVE, .RUNNING, .SUCCEEDED { background: #e2f4e6; color: #1d7a33; }
  .DEAD, .FAILED { background: #fbe3e4; color: #b3262e; }
  .PENDING, .RESTARTING { background: #fdf3d7; color: #8a6d0a; }
  .bar { background: #edf0f3; border-radius: 4px; height: 10px;
         width: 120px; display: inline-block; vertical-align: middle; }
  .bar i { display: block; height: 100%; border-radius: 4px;
           background: #3d7fd9; }
  .muted { color: #8a93a0; }
  code { font-size: 12px; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1>
  <span id="summary">connecting…</span></header>
<main>
  <section><h2>Nodes</h2><table id="nodes"></table></section>
  <section><h2>Actors</h2><table id="actors"></table></section>
  <section><h2>Jobs</h2><table id="jobs"></table></section>
  <section><h2>Serve</h2><pre id="serve" class="muted"></pre></section>
  <section><h2>Events</h2><table id="events"></table></section>
</main>
<script>
const fmtB = (b) => b >= 1<<30 ? (b/(1<<30)).toFixed(1)+'G'
  : b >= 1<<20 ? (b/(1<<20)).toFixed(0)+'M' : b + 'B';
const bar = (pct) =>
  `<span class="bar"><i style="width:${Math.min(100, pct||0)}%"></i></span>
   <span class="muted">${(pct||0).toFixed(0)}%</span>`;
const esc = (s) => String(s).replace(/[&<>"']/g, c => ({'&':'&amp;',
  '<':'&lt;', '>':'&gt;', '"':'&quot;', "'":'&#39;'}[c]));
const pill = (s) => `<span class="pill ${/^[A-Z_]+$/.test(s) ? s : ''}">` +
  `${esc(s)}</span>`;
const row = (cells) => '<tr>' + cells.map(c => `<td>${c}</td>`).join('') +
  '</tr>';
const head = (cols) => '<tr>' + cols.map(c => `<th>${c}</th>`).join('') +
  '</tr>';

async function j(path) {
  const r = await fetch(path);
  return r.json();
}

async function refresh() {
  try {
    const nodes = await j('/api/nodes');
    const alive = nodes.filter(n => n.state === 'ALIVE').length;
    let cpus = 0;
    nodes.forEach(n => { cpus += (n.resources_total.CPU || 0); });
    document.getElementById('summary').textContent =
      `${alive}/${nodes.length} nodes alive · ${cpus} CPUs · ` +
      new Date().toLocaleTimeString();
    document.getElementById('nodes').innerHTML =
      head(['node', 'state', 'address', 'cpu', 'mem', 'store',
            'workers', 'resources']) +
      nodes.map(n => {
        const s = n.node_stats || {};
        const storePct = s.object_store_capacity ?
          100 * s.object_store_used / s.object_store_capacity : 0;
        return row([
          `<code>${esc(n.node_id.slice(0, 10))}</code>`, pill(n.state),
          esc(`${n.address[0]}:${n.address[1]}`),
          bar(s.cpu_percent), bar(s.mem_percent), bar(storePct),
          s.workers ?? '—',
          `<code>${esc(JSON.stringify(n.resources_total))}</code>`]);
      }).join('');

    const actors = await j('/api/actors');
    document.getElementById('actors').innerHTML =
      head(['actor', 'class', 'state', 'restarts', 'node']) +
      actors.slice(0, 50).map(a => row([
        `<code>${esc((a.actor_id||'').slice(0, 10))}</code>`,
        esc(a.class_name || '—'), pill(a.state || '—'),
        a.num_restarts ?? 0,
        `<code>${esc((a.node_id||'').slice(0, 10) || '—')}</code>`]))
      .join('');

    const jobs = await j('/api/jobs');
    document.getElementById('jobs').innerHTML =
      head(['job', 'status', 'entrypoint']) +
      jobs.slice(0, 20).map(x => row([
        `<code>${esc(x.submission_id || x.job_id || '')}</code>`,
        pill(x.status || '—'),
        `<code>${esc((x.entrypoint||'').slice(0, 80))}</code>`]))
      .join('');

    const serve = await j('/api/serve');
    document.getElementById('serve').textContent =
      JSON.stringify(serve, null, 1).slice(0, 2000);

    const events = await j('/api/events');
    document.getElementById('events').innerHTML =
      head(['severity', 'source', 'message']) +
      events.slice(-25).reverse().map(e => row([
        pill(e.severity || 'INFO'), esc(e.source || '—'),
        esc((e.message || '').slice(0, 140))])).join('');
  } catch (err) {
    document.getElementById('summary').textContent = 'error: ' + err;
  }
}
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
