"""The dashboard's web UI: a dependency-free multi-view operator app.

Reference role: the dashboard React client (dashboard/client/src/App.tsx
+ components/) — re-scoped to a single self-contained page with hash
routing over the head's JSON endpoints.  Views: Overview, Nodes,
Actors, Tasks, Objects, Placement Groups, Jobs (with per-job detail +
live log tail), Serve, Tune, Events.  No build step, no bundler: the
head serves this string at "/ui" (and "/").
"""

INDEX_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f6f7f9; color: #1a1c20; }
  header { background: #14202e; color: #fff; padding: 10px 20px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 17px; margin: 0; font-weight: 600; }
  header span { color: #9fb2c8; font-size: 12px; }
  nav { background: #1d2d40; padding: 0 20px; display: flex; gap: 2px;
        overflow-x: auto; }
  nav a { color: #9fb2c8; text-decoration: none; font-size: 13px;
          padding: 8px 12px; border-bottom: 2px solid transparent;
          white-space: nowrap; }
  nav a.active { color: #fff; border-bottom-color: #3d7fd9; }
  main { padding: 16px 20px; max-width: 1280px; margin: 0 auto; }
  section { background: #fff; border: 1px solid #e3e6ea;
            border-radius: 8px; margin-bottom: 16px; padding: 12px 16px; }
  h2 { font-size: 13px; text-transform: uppercase; letter-spacing: .06em;
       color: #5a6472; margin: 0 0 8px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th { text-align: left; color: #5a6472; font-weight: 600;
       border-bottom: 1px solid #e3e6ea; padding: 4px 10px 4px 0; }
  td { border-bottom: 1px solid #f0f2f4; padding: 4px 10px 4px 0;
       font-variant-numeric: tabular-nums; }
  .pill { display: inline-block; padding: 1px 8px; border-radius: 10px;
          font-size: 12px; background: #edf0f3; color: #39414d; }
  .ALIVE, .RUNNING, .SUCCEEDED, .HEALTHY, .TERMINATED, .FINISHED
    { background: #e2f4e6; color: #1d7a33; }
  .DEAD, .FAILED, .ERROR, .UNHEALTHY { background: #fbe3e4;
                                        color: #b3262e; }
  .PENDING, .RESTARTING, .PAUSED, .UPDATING { background: #fdf3d7;
                                              color: #8a6d0a; }
  .bar { background: #edf0f3; border-radius: 4px; height: 10px;
         width: 120px; display: inline-block; vertical-align: middle; }
  .bar i { display: block; height: 100%; border-radius: 4px;
           background: #3d7fd9; }
  .muted { color: #8a93a0; }
  code { font-size: 12px; }
  pre.logs { background: #14202e; color: #d7e3f0; padding: 12px;
             border-radius: 6px; font-size: 12px; max-height: 480px;
             overflow: auto; white-space: pre-wrap; }
  a.rowlink { color: #2b66c2; text-decoration: none; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1><span id="summary">connecting…</span></header>
<nav id="nav"></nav>
<main id="view"></main>
<script>
const VIEWS = ['overview', 'nodes', 'actors', 'tasks', 'objects', 'pgs',
               'jobs', 'serve', 'tune', 'events'];
const TITLES = {overview: 'Overview', nodes: 'Nodes', actors: 'Actors',
  tasks: 'Tasks', objects: 'Objects', pgs: 'Placement Groups',
  jobs: 'Jobs', serve: 'Serve', tune: 'Tune', events: 'Events'};

const fmtB = (b) => b >= 1<<30 ? (b/(1<<30)).toFixed(1)+'G'
  : b >= 1<<20 ? (b/(1<<20)).toFixed(0)+'M' : (b||0) + 'B';
const bar = (pct) =>
  `<span class="bar"><i style="width:${Math.min(100, pct||0)}%"></i></span>
   <span class="muted">${(pct||0).toFixed(0)}%</span>`;
const esc = (s) => String(s ?? '').replace(/[&<>"']/g, c => ({'&':'&amp;',
  '<':'&lt;', '>':'&gt;', '"':'&quot;', "'":'&#39;'}[c]));
const pill = (s) => `<span class="pill ${/^[A-Z_]+$/.test(s) ? s : ''}">` +
  `${esc(s)}</span>`;
const row = (cells) => '<tr>' + cells.map(c => `<td>${c}</td>`).join('') +
  '</tr>';
const head = (cols) => '<tr>' + cols.map(c => `<th>${c}</th>`).join('') +
  '</tr>';
const section = (title, body, id) =>
  `<section id="${id||''}"><h2>${title}</h2>${body}</section>`;
const sid = (s, n=10) => `<code>${esc(String(s||'').slice(0, n))}</code>`;

async function j(path) { return (await fetch(path)).json(); }

async function summary() {
  try {
    const nodes = await j('/api/nodes');
    const alive = nodes.filter(n => n.state === 'ALIVE').length;
    let cpus = 0;
    nodes.forEach(n => { cpus += (n.resources_total.CPU || 0); });
    document.getElementById('summary').textContent =
      `${alive}/${nodes.length} nodes alive · ${cpus} CPUs · ` +
      new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById('summary').textContent = 'error: ' + e;
  }
}

// ------------------------------------------------------------- views
async function vOverview() {
  const [nodes, actors, jobs, events] = await Promise.all([
    j('/api/nodes'), j('/api/actors'), j('/api/jobs'),
    j('/api/events')]);
  return section('Nodes', nodesTable(nodes)) +
    section('Actors (50 newest)', actorsTable(actors.slice(0, 50))) +
    section('Jobs', jobsTable(jobs.slice(0, 20))) +
    section('Recent events', eventsTable(events.slice(-15)));
}

function nodesTable(nodes) {
  return '<table>' + head(['node', 'state', 'address', 'cpu', 'mem',
                           'store', 'workers', 'resources']) +
    nodes.map(n => {
      const s = n.node_stats || {};
      const storePct = s.object_store_capacity ?
        100 * s.object_store_used / s.object_store_capacity : 0;
      return row([sid(n.node_id), pill(n.state),
        esc(`${n.address[0]}:${n.address[1]}`),
        bar(s.cpu_percent), bar(s.mem_percent), bar(storePct),
        s.workers ?? '—',
        `<code>${esc(JSON.stringify(n.resources_total))}</code>`]);
    }).join('') + '</table>';
}
async function vNodes() {
  return section('Nodes', nodesTable(await j('/api/nodes')));
}

function actorsTable(actors) {
  return '<table>' + head(['actor', 'name', 'class', 'state',
                           'restarts', 'node', 'pid']) +
    actors.map(a => row([sid(a.actor_id), esc(a.name || '—'),
      esc(a.class_name || '—'), pill(a.state || '—'),
      a.num_restarts ?? 0, sid(a.node_id || '—'),
      a.pid ?? '—'])).join('') + '</table>';
}
async function vActors() {
  return section('Actors', actorsTable(await j('/api/actors')));
}

async function vTasks() {
  const tasks = await j('/api/tasks');
  return section('Tasks (200 newest)', '<table>' +
    head(['task', 'name', 'state', 'node', 'worker']) +
    tasks.slice(-200).reverse().map(t => row([
      sid(t.task_id), esc(t.name || t.func_name || '—'),
      pill(t.state || '—'), sid(t.node_id || '—'),
      sid(t.worker_id || '—')])).join('') + '</table>');
}

async function vObjects() {
  const objs = await j('/api/objects');
  return section('Objects (200 newest)', '<table>' +
    head(['object', 'size', 'state', 'node', 'pinned']) +
    objs.slice(-200).reverse().map(o => row([
      sid(o.object_id, 14), fmtB(o.size), pill(o.state || '—'),
      sid(o.node_id || '—'), o.pinned ?? '—'])).join('') + '</table>');
}

async function vPgs() {
  const pgs = await j('/api/placement_groups');
  return section('Placement groups', '<table>' +
    head(['pg', 'name', 'state', 'strategy', 'bundles']) +
    pgs.map(p => row([sid(p.pg_id || p.placement_group_id),
      esc(p.name || '—'), pill(p.state || '—'),
      esc(p.strategy || '—'),
      `<code>${esc(JSON.stringify(p.bundles))}</code>`]))
    .join('') + '</table>');
}

function jobsTable(jobs) {
  return '<table>' + head(['job', 'status', 'entrypoint', '']) +
    jobs.map(x => {
      const id = x.submission_id || x.job_id || '';
      const link = x.submission_id ?
        `<a class="rowlink" href="#/jobs/${esc(id)}">logs →</a>` : '';
      return row([sid(id, 16), pill(x.status || '—'),
        `<code>${esc((x.entrypoint||'').slice(0, 80))}</code>`, link]);
    }).join('') + '</table>';
}
async function vJobs(arg) {
  if (arg) return vJobDetail(arg);
  return section('Jobs', jobsTable(await j('/api/jobs')));
}

async function vJobDetail(sid_) {
  let info = {};
  try { info = await j('/api/jobs/' + sid_); } catch (e) {}
  const logs = await (await fetch(
    '/api/jobs/' + sid_ + '/logs')).text();
  return section(`Job ${esc(sid_)} — ${esc(info.status || '?')}`,
    `<p><code>${esc(info.entrypoint || '')}</code></p>` +
    `<pre class="logs" id="joblogs">${esc(logs)}</pre>` +
    `<p><a class="rowlink" href="#/jobs">← all jobs</a></p>`);
}

async function vServe() {
  const st = await j('/api/serve');
  if (!Array.isArray(st)) {
    return section('Serve', `<pre class="muted">` +
      `${esc(JSON.stringify(st, null, 1))}</pre>`);
  }
  return section('Serve deployments', '<table>' +
    head(['deployment', 'status', 'replicas', 'version', 'detail']) +
    st.map(d => row([esc(d.name || '—'), pill(d.status || '—'),
      d.num_replicas ?? d.replicas ?? '—', esc(d.version ?? '—'),
      `<code>${esc(JSON.stringify(d.message || d.detail || ''))
        .slice(0, 120)}</code>`])).join('') + '</table>');
}

async function vTune() {
  const exps = await j('/api/tune');
  if (!exps.length) {
    return section('Tune', '<p class="muted">no experiments</p>');
  }
  return exps.map(e => {
    const counts = {};
    (e.trials || []).forEach(t => {
      counts[t.status] = (counts[t.status] || 0) + 1; });
    const sub = Object.entries(counts)
      .map(([k, v]) => `${v} ${esc(k)}`).join(' · ');
    return section(`Experiment ${esc(e.name)} — ${sub}`, '<table>' +
      head(['trial', 'status', 'config', 'last result']) +
      (e.trials || []).map(t => row([sid(t.trial_id),
        pill(t.status || '—'),
        `<code>${esc(JSON.stringify(t.config)).slice(0, 90)}</code>`,
        `<code>${esc(JSON.stringify(t.last_result)).slice(0, 110)}` +
        `</code>`])).join('') + '</table>');
  }).join('');
}

function eventsTable(events) {
  return '<table>' + head(['severity', 'source', 'message']) +
    events.slice().reverse().map(e => row([
      pill(e.severity || 'INFO'), esc(e.source || '—'),
      esc((e.message || '').slice(0, 140))])).join('') + '</table>';
}
async function vEvents() {
  return section('Events', eventsTable((await j('/api/events'))
    .slice(-100)));
}

const RENDER = {overview: vOverview, nodes: vNodes, actors: vActors,
  tasks: vTasks, objects: vObjects, pgs: vPgs, jobs: vJobs,
  serve: vServe, tune: vTune, events: vEvents};

function route() {
  const h = (location.hash || '#/overview').replace(/^#\\//, '');
  const parts = h.split('/');
  const view = VIEWS.includes(parts[0]) ? parts[0] : 'overview';
  return {view, arg: parts[1]};
}

function drawNav() {
  const {view} = route();
  document.getElementById('nav').innerHTML = VIEWS.map(v =>
    `<a href="#/${v}" class="${v === view ? 'active' : ''}">` +
    `${TITLES[v]}</a>`).join('');
}

async function refresh(isTick) {
  const {view, arg} = route();
  drawNav();
  // Interval re-renders must not yank the operator's place in a log
  // they scrolled through; tail-follow only when already at the end.
  const prev = document.getElementById('joblogs');
  const keep = isTick === true && prev ? {
    top: prev.scrollTop,
    atEnd: prev.scrollTop + prev.clientHeight >= prev.scrollHeight - 4,
  } : null;
  try {
    document.getElementById('view').innerHTML =
      await RENDER[view](arg);
  } catch (err) {
    document.getElementById('view').innerHTML =
      section('Error', `<pre class="muted">${esc(err)}</pre>`);
  }
  const cur = document.getElementById('joblogs');
  if (keep && cur) {
    cur.scrollTop = keep.atEnd ? cur.scrollHeight : keep.top;
  }
  summary();
}
window.addEventListener('hashchange', () => refresh(false));
refresh(false);
setInterval(() => refresh(true), 3000);
</script>
</body>
</html>
"""
