"""Dashboard: HTTP observability head (reference: dashboard/)."""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard  # noqa: F401

__all__ = ["DashboardHead", "start_dashboard"]
