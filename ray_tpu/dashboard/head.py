"""Dashboard head: HTTP observability endpoint for the cluster.

Reference: dashboard/head.py + modules (node/actor/state/reporter) and
the Prometheus exposition flow (_private/metrics_agent.py -> scrape).
Scoped: one aiohttp actor serving JSON state (the reference's REST
surface) + /metrics in Prometheus text, aggregated from the telemetry
snapshots every process pushes to the GCS KV.
"""

from __future__ import annotations

import asyncio
import json
import pickle
from typing import Dict, Optional

import ray_tpu

DASHBOARD_NAME = "RT_DASHBOARD"


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._ready = asyncio.Event()

    async def run(self):
        from aiohttp import web

        routes = web.RouteTableDef()

        loop = asyncio.get_running_loop()

        def _json(data):
            return web.json_response(text=json.dumps(data, default=str))

        async def _call(fn, *args, **kwargs):
            # State APIs are sync (they block on the CoreWorker's IO
            # loop, which is THIS loop) — always run them off-loop.
            import functools
            return await loop.run_in_executor(
                None, functools.partial(fn, *args, **kwargs))

        @routes.get("/")
        async def index(request):
            from ray_tpu.experimental import state
            return _json({
                "cluster": await _call(ray_tpu.cluster_resources),
                "available": await _call(ray_tpu.available_resources),
                "nodes": await _call(state.list_nodes),
            })

        @routes.get("/ui")
        async def ui(request):
            # The web client (reference: dashboard/client React app —
            # scoped to one dependency-free page polling the JSON API).
            from ray_tpu.dashboard.ui import INDEX_HTML
            return web.Response(text=INDEX_HTML,
                                content_type="text/html")

        @routes.get("/api/nodes")
        async def nodes(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.list_nodes))

        @routes.get("/api/actors")
        async def actors(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.list_actors, detail=True))

        @routes.get("/api/tasks")
        async def tasks(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.list_tasks))

        @routes.get("/api/objects")
        async def objects(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.list_objects))

        @routes.get("/api/objects/summary")
        async def objects_summary(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.summarize_objects))

        @routes.get("/api/placement_groups")
        async def pgs(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.list_placement_groups))

        def _submission_records():
            """Submitted-job records, logs stripped (shared by
            /api/jobs and /api/submissions)."""
            from ray_tpu.job_submission import JobSubmissionClient
            try:
                subs = JobSubmissionClient().list_jobs()
            except Exception:
                return []
            for s in subs:
                s.pop("logs", None)
            return subs

        @routes.get("/api/jobs")
        async def jobs(request):
            """Driver jobs + submitted jobs in one listing (reference:
            job_head merges submission records with job-table rows)."""
            from ray_tpu.experimental import state
            out = list(await _call(state.list_jobs))
            out += await _call(_submission_records)
            return _json(out)

        @routes.get("/api/submissions")
        async def submissions(request):
            """Submitted jobs ONLY (stable shape for the SDK's
            list_jobs; /api/jobs merges driver jobs in for the UI)."""
            return _json(await _call(_submission_records))

        @routes.post("/api/jobs")
        async def submit_job(request):
            """Remote job submission over plain HTTP (reference:
            dashboard/modules/job/job_head.py POST /api/jobs/): body
            {"entrypoint": "...", "submission_id"?, "runtime_env"?}."""
            from ray_tpu.job_submission import JobSubmissionClient
            payload = await request.json()
            if not payload.get("entrypoint"):
                return web.json_response(
                    {"error": "missing entrypoint"}, status=400)

            def _submit():
                client = JobSubmissionClient()
                return client.submit_job(
                    entrypoint=payload["entrypoint"],
                    submission_id=payload.get("submission_id"),
                    runtime_env=payload.get("runtime_env"))

            try:
                sid = await _call(_submit)
            except Exception as e:
                return web.json_response({"error": repr(e)}, status=500)
            return _json({"submission_id": sid})

        @routes.get("/api/jobs/{submission_id}")
        async def job_info(request):
            from ray_tpu.job_submission import JobSubmissionClient
            sid = request.match_info["submission_id"]
            try:
                info = await _call(
                    lambda: JobSubmissionClient().get_job_info(sid))
            except KeyError:
                return web.json_response({"error": "no such job"},
                                         status=404)
            info.pop("logs", None)
            return _json(info)

        @routes.get("/api/jobs/{submission_id}/logs")
        async def job_logs(request):
            """Job logs; `?follow=1` streams chunks until the job
            reaches a terminal state (reference: job_head log
            tailing)."""
            from ray_tpu.job_submission import JobStatus, \
                JobSubmissionClient
            sid = request.match_info["submission_id"]
            client = JobSubmissionClient()
            if request.query.get("follow") not in ("1", "true"):
                logs = await _call(lambda: client.get_job_logs(sid))
                return web.Response(text=logs,
                                    content_type="text/plain")
            resp = web.StreamResponse()
            resp.content_type = "text/plain"
            await resp.prepare(request)
            sent = 0
            while True:
                try:
                    rec = await _call(client.get_job_info, sid)
                except KeyError:
                    break
                from ray_tpu.job_submission import _window_delta
                chunk, sent = _window_delta(rec, sent)
                if chunk:
                    await resp.write(chunk.encode())
                if rec.get("status") in JobStatus.TERMINAL:
                    break
                await asyncio.sleep(0.5)
            await resp.write_eof()
            return resp

        @routes.post("/api/jobs/{submission_id}/stop")
        async def job_stop(request):
            from ray_tpu.job_submission import JobSubmissionClient
            sid = request.match_info["submission_id"]
            ok = await _call(
                lambda: JobSubmissionClient().stop_job(sid))
            return _json({"stopped": bool(ok)})

        @routes.put("/api/serve/applications")
        async def serve_deploy(request):
            """REST deploy (reference: serve REST schema / PUT
            api/serve/applications): the declarative config shape,
            schema-validated (serve/schema.py)."""
            from ray_tpu.serve import schema as serve_schema
            payload = await request.json()
            try:
                deployed = await _call(serve_schema.apply_config,
                                       payload)
            except Exception as e:
                return web.json_response({"error": repr(e)}, status=400)
            return _json({"deployed": deployed})

        @routes.get("/api/serve")
        async def serve_status(request):
            try:
                from ray_tpu import serve as serve_mod
                return _json(await _call(serve_mod.status))
            except Exception as e:
                return _json({"error": repr(e)})

        @routes.get("/api/tune")
        async def tune_experiments(request):
            """Experiments published by TrialRunner to the "tune" KV
            namespace (reference: the dashboard tune module reading
            experiment state through the head)."""
            import json as _json_mod
            w = ray_tpu._private.worker.global_worker
            keys = (await w._gcs_request(
                "kv_keys", {"ns": "tune", "prefix": b""}))["keys"]
            out = []
            for key in keys:
                blob = (await w._gcs_request(
                    "kv_get", {"ns": "tune", "key": key}))["value"]
                if blob is None:
                    continue
                try:
                    out.append(_json_mod.loads(blob))
                except Exception:
                    continue
            out.sort(key=lambda e: -e.get("updated_at", 0))
            return _json(out)

        @routes.get("/api/events")
        async def events(request):
            from ray_tpu.experimental import state
            return _json(await _call(state.list_cluster_events))

        @routes.get("/api/timeline")
        async def timeline(request):
            return _json(await _call(ray_tpu.timeline))

        @routes.get("/metrics")
        async def metrics(request):
            from ray_tpu.util.metrics import (prometheus_text,
                                              registry_snapshot)
            w = ray_tpu._private.worker.global_worker
            keys = (await w._gcs_request(
                "kv_keys", {"ns": "telemetry", "prefix": b""}))["keys"]
            snaps = list(registry_snapshot())
            for key in keys:
                blob = (await w._gcs_request(
                    "kv_get", {"ns": "telemetry", "key": key}))["value"]
                if blob is None:
                    continue
                try:
                    snaps.extend(pickle.loads(blob).get("snapshots", []))
                except Exception:
                    continue
            return web.Response(text=prometheus_text(snaps),
                                content_type="text/plain")

        app = web.Application()
        app.add_routes(routes)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        for sock in site._server.sockets:  # noqa: SLF001
            self.port = sock.getsockname()[1]
            break
        self._ready.set()
        return {"host": self.host, "port": self.port}

    async def ready(self) -> Dict:
        await self._ready.wait()
        return {"host": self.host, "port": self.port}


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> Dict:
    """Start (or connect to) the dashboard head actor; returns its
    address."""
    try:
        head = ray_tpu.get_actor(DASHBOARD_NAME)
    except Exception:
        cls = ray_tpu.remote(DashboardHead)
        head = cls.options(name=DASHBOARD_NAME, lifetime="detached",
                           num_cpus=0.1, max_concurrency=100).remote(
            host, port)
        head.run.options(num_returns=0).remote()
    return ray_tpu.get(head.ready.remote(), timeout=60)
