"""Multi-node-in-one-process test cluster.

Reference: python/ray/cluster_utils.py — Cluster.add_node (:99) spawns a
real raylet with its own resources so distributed scheduling and
fault-tolerance paths run without any cloud; remove_node (:165) kills it
mid-run for fault injection.
"""

from __future__ import annotations

from ray_tpu._private.api import _ensure_loop
from ray_tpu._private.node import InProcessNode, new_session_dir


class Cluster:
    def __init__(self):
        self.loop = _ensure_loop()
        self.session_dir = new_session_dir()
        self.head: InProcessNode | None = None
        self.nodes: list[InProcessNode] = []
        self._connected = False

    @property
    def gcs_addr(self):
        return self.head.gcs_addr if self.head else None

    @property
    def address(self) -> str | None:
        if self.head is None:
            return None
        return f"{self.head.gcs_addr[0]}:{self.head.gcs_addr[1]}"

    def add_node(self, num_cpus=1, num_tpus=None, resources=None,
                 labels=None, object_store_memory=None, node_name=None):
        head = self.head is None
        node = InProcessNode(
            self.loop, head=head,
            gcs_addr=None if head else self.head.gcs_addr,
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
            labels=labels, object_store_memory=object_store_memory,
            session_dir=self.session_dir, node_name=node_name).start()
        if head:
            self.head = node
        self.nodes.append(node)
        return node

    def remove_node(self, node: InProcessNode):
        """Kill a raylet mid-run (fault injection; reference:
        cluster_utils.py:165)."""
        node.kill(stop_gcs=False)
        if node in self.nodes:
            self.nodes.remove(node)

    def connect(self, **kwargs):
        import ray_tpu
        from ray_tpu._private import worker as worker_mod
        from ray_tpu._private.worker import CoreWorker, MODE_DRIVER
        import asyncio
        if self.head is None:
            raise RuntimeError("add a head node first")
        raylet = self.head.raylet
        cw = CoreWorker(MODE_DRIVER, self.head.gcs_addr,
                        raylet_addr=self.head.raylet_addr,
                        store_path=raylet.store_path,
                        store_cap=raylet.store_capacity)
        cw.loop = self.loop
        asyncio.run_coroutine_threadsafe(cw._connect(), self.loop).result(60)
        cw.connected = True
        worker_mod.global_worker = cw
        self._connected = True
        return cw

    def wait_for_nodes(self, count=None, timeout=60.0):
        import asyncio
        from ray_tpu._private import protocol

        count = count if count is not None else len(self.nodes)

        async def _wait():
            conn = await protocol.Connection.connect(
                self.head.gcs_addr[0], self.head.gcs_addr[1], name="waiter")
            ok = await conn.request("wait_for_nodes",
                                    {"count": count, "timeout": timeout})
            await conn.close()
            return ok

        return asyncio.run_coroutine_threadsafe(
            _wait(), self.loop).result(timeout + 10)

    # ------------------------------------------------ fault injection
    # Message-level faults (ray_tpu._private.failpoints): all cluster
    # members live in THIS process, so installing connection rules here
    # re-resolves every live link immediately.
    def partition(self, a, b, one_way: bool = False):
        """Cut the link between two members (either may be "gcs").
        one_way=True drops only a→b traffic (half-open link)."""
        from ray_tpu._private.test_utils import partition
        partition(a, b, one_way=one_way)

    def slow_link(self, a, b, delay_s: float):
        """Add delay_s of one-way latency between two members."""
        from ray_tpu._private.test_utils import slow_link
        slow_link(a, b, delay_s)

    def heal(self):
        """Remove every partition / slow-link rule."""
        from ray_tpu._private.test_utils import heal
        heal()

    def restart_gcs(self):
        """Kill and restart the head GCS on the same port, reloading state
        from its snapshot (reference: GCS failover with Redis persistence,
        redis_store_client.h:28; raylets reconnect via the
        NotifyGCSRestart-equivalent re-register path)."""
        import asyncio

        async def _do():
            head = self.head
            old = head.gcs_server
            port = head.gcs_addr[1]
            persist = old._persist_path
            await old.stop()
            from ray_tpu._private.gcs import GcsServer
            new = GcsServer(persist_path=persist)
            await new.start(port)
            head.gcs_server = new

        asyncio.run_coroutine_threadsafe(_do(), self.loop).result(60)

    def shutdown(self):
        import ray_tpu
        from ray_tpu._private import worker as worker_mod
        if self._connected and worker_mod.global_worker is not None:
            worker_mod.global_worker.shutdown()
            worker_mod.global_worker = None
        for node in list(reversed(self.nodes)):
            node.kill(stop_gcs=node is self.head)
        self.nodes.clear()
        self.head = None


class ProcessCluster:
    """Multi-node cluster of REAL OS processes (one GCS process + one
    raylet process per node), for SIGKILL-grade fault injection and for
    validating the actual deployment topology (reference:
    python/ray/cluster_utils.py Cluster — each add_node spawns a real
    raylet process; tests kill them mid-run)."""

    def __init__(self, host: str = "127.0.0.1"):
        from ray_tpu._private.node import new_session_dir
        self.host = host
        self.session_dir = new_session_dir()
        self.head = None
        self.nodes: list = []
        self._connected = False
        self._raylet_pids: set[int] = set()

    @property
    def gcs_addr(self):
        return self.head.gcs_addr if self.head else None

    @property
    def address(self) -> str | None:
        if self.head is None:
            return None
        return f"{self.head.gcs_addr[0]}:{self.head.gcs_addr[1]}"

    def add_node(self, num_cpus=1, num_tpus=None, resources=None,
                 labels=None, object_store_memory=None, node_name=None):
        from ray_tpu._private.node import NodeProcesses
        head = self.head is None
        node = NodeProcesses(
            session_dir=self.session_dir, head=head,
            gcs_addr=None if head else self.head.gcs_addr,
            host=self.host, num_cpus=num_cpus, num_tpus=num_tpus,
            resources=resources, labels=labels,
            object_store_memory=object_store_memory,
            node_name=node_name).start()
        if head:
            self.head = node
        self.nodes.append(node)
        self._raylet_pids.add(node.raylet_proc.pid)
        return node

    def remove_node(self, node, graceful: bool = False):
        """SIGKILL a node's raylet process (real fault injection; its
        workers die when the raylet socket closes)."""
        import signal
        node.kill_raylet(sig=signal.SIGTERM if graceful else signal.SIGKILL)
        if node in self.nodes:
            self.nodes.remove(node)

    def kill_gcs(self):
        self.head.kill_gcs()

    def restart_gcs(self):
        self.head.restart_gcs()

    def connect(self, **kwargs):
        """Connect this process as a driver, exactly the way an external
        `ray_tpu.init(address=...)` driver would (raylet discovery + store
        path from the register reply)."""
        import ray_tpu
        cw = ray_tpu.init(address=self.address, **kwargs)
        self._connected = True
        return cw

    def wait_for_nodes(self, count=None, timeout=60.0):
        import asyncio
        from ray_tpu._private import protocol
        from ray_tpu._private.api import _ensure_loop

        count = count if count is not None else len(self.nodes)
        loop = _ensure_loop()

        async def _wait():
            conn = await protocol.Connection.connect(
                self.head.gcs_addr[0], self.head.gcs_addr[1], name="waiter")
            ok = await conn.request("wait_for_nodes",
                                    {"count": count, "timeout": timeout})
            await conn.close()
            return ok

        return asyncio.run_coroutine_threadsafe(
            _wait(), loop).result(timeout + 10)

    def shutdown(self):
        import glob
        import ray_tpu
        from ray_tpu._private import worker as worker_mod
        if self._connected and worker_mod.global_worker is not None:
            ray_tpu.shutdown()
        pids = set(self._raylet_pids)
        for node in list(reversed(self.nodes)):
            node.kill()
        self.nodes.clear()
        self.head = None
        # SIGKILLed raylets can't clean their shm arenas; sweep ONLY this
        # cluster's (the arena filename ends with the raylet's pid).
        import os
        for pid in pids:
            for path in glob.glob(f"/dev/shm/rt_store_*_{pid}"):
                try:
                    os.unlink(path)
                except OSError:
                    pass
