"""Autoscaler: demand-driven scaling with pluggable node providers
(reference: python/ray/autoscaler — SURVEY.md §2.2)."""

from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    LocalProcessNodeProvider,
    FakeMultiNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler._private.autoscaler import (  # noqa: F401
    Monitor,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.config import (  # noqa: F401
    ClusterConfigError,
    load_cluster_config,
    validate_cluster_config,
)
from ray_tpu.autoscaler.tpu_pod_provider import (  # noqa: F401
    MockQueuedResourceAPI,
    TPUPodProvider,
)

__all__ = ["ClusterConfigError", "FakeMultiNodeProvider",
           "LocalProcessNodeProvider", "MockQueuedResourceAPI",
           "Monitor", "NodeProvider", "StandardAutoscaler",
           "TPUPodProvider", "load_cluster_config",
           "validate_cluster_config"]
