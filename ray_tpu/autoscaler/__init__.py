"""Autoscaler: demand-driven scaling with pluggable node providers
(reference: python/ray/autoscaler — SURVEY.md §2.2)."""

from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    LocalProcessNodeProvider,
    FakeMultiNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler._private.autoscaler import (  # noqa: F401
    Monitor,
    StandardAutoscaler,
)

__all__ = ["FakeMultiNodeProvider", "LocalProcessNodeProvider",
           "Monitor", "NodeProvider", "StandardAutoscaler"]
