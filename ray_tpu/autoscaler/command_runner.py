"""Command runners: how the cluster launcher executes bootstrap commands
on nodes.

Reference: python/ray/autoscaler/_private/command_runner.py
(SSHCommandRunner/DockerCommandRunner) + updater.py (NodeUpdater running
setup_commands then the start command).  Two runners ship in-tree:
subprocess (same host — the process provider's transport) and ssh
(remote hosts; the TPU-pod path runs `gcloud compute tpus tpu-vm ssh`
or plain ssh to each slice host).
"""

from __future__ import annotations

import subprocess
from typing import Dict, List, Optional


class CommandRunnerError(RuntimeError):
    def __init__(self, cmd: str, rc: int, output: str):
        super().__init__(f"command failed (rc={rc}): {cmd}\n{output}")
        self.cmd = cmd
        self.rc = rc
        self.output = output


class SubprocessCommandRunner:
    """Runs node commands as local subprocesses (the fake/local-process
    providers' transport)."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self.env = env

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        import os
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        proc = subprocess.run(cmd, shell=True, env=env,
                              capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise CommandRunnerError(cmd, proc.returncode,
                                     proc.stdout + proc.stderr)
        return proc.stdout


class SSHCommandRunner:
    """Runs node commands over ssh (reference: SSHCommandRunner —
    same option set: key file, user, connection hardening flags)."""

    def __init__(self, host: str, user: Optional[str] = None,
                 ssh_key: Optional[str] = None,
                 ssh_options: Optional[List[str]] = None):
        self.host = host
        self.user = user
        self.ssh_key = ssh_key
        self.ssh_options = ssh_options or [
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "ConnectTimeout=10",
        ]

    def _ssh_argv(self, cmd: str) -> List[str]:
        argv = ["ssh"] + list(self.ssh_options)
        if self.ssh_key:
            argv += ["-i", self.ssh_key]
        target = f"{self.user}@{self.host}" if self.user else self.host
        return argv + [target, cmd]

    def run(self, cmd: str, timeout: float = 600.0) -> str:
        proc = subprocess.run(self._ssh_argv(cmd), capture_output=True,
                              text=True, timeout=timeout)
        if proc.returncode != 0:
            raise CommandRunnerError(cmd, proc.returncode,
                                     proc.stdout + proc.stderr)
        return proc.stdout


class NodeUpdater:
    """Bootstrap one node: run setup commands, then the start command
    (reference: _private/updater.py NodeUpdater.do_update)."""

    def __init__(self, runner, setup_commands: List[str],
                 start_command: str):
        self.runner = runner
        self.setup_commands = setup_commands
        self.start_command = start_command

    def update(self) -> None:
        for cmd in self.setup_commands:
            self.runner.run(cmd)
        if self.start_command:
            self.runner.run(self.start_command)
