"""Cloud TPU v2 queued-resources REST client for TPUPodProvider.

Reference role: python/ray/autoscaler/_private/gcp/node_provider.py —
the reference's GCP provider wraps the googleapiclient discovery
surface; here the client speaks the Cloud TPU REST schema directly
(https://tpu.googleapis.com/v2) so the ONLY fake in tests is the HTTP
transport: requests serialize byte-identically to what the real
service receives.

Endpoints used (Cloud TPU API v2, queued-resources acquisition model):

  POST   /v2/projects/{p}/locations/{z}/queuedResources
             ?queuedResourceId={id}
         body: {"tpu": {"nodeSpec": [{"parent": ..., "nodeId": ...,
                "node": {"acceleratorType": ..., "runtimeVersion": ...,
                         "networkConfig": {"enableExternalIps": ...}}}]},
                "queueingPolicy": {...}}      -> long-running Operation
  GET    /v2/projects/{p}/locations/{z}/queuedResources/{id}
         -> {"name": ..., "state": {"state": "WAITING_FOR_RESOURCES" |
             "PROVISIONING" | "ACTIVE" | "FAILED" | "SUSPENDED" | ...}}
  GET    /v2/projects/{p}/locations/{z}/nodes/{nodeId}
         -> {"state": "READY", "networkEndpoints":
             [{"ipAddress": ..., "port": ...}, ...]}  (one per host VM)
  DELETE /v2/.../queuedResources/{id}?force=true
  GET    /v2/.../queuedResources  -> {"queuedResources": [...]}

A TPU pod slice is ONE Node resource; its networkEndpoints carry one
entry per host VM, which is exactly the provider's hosts list.

The transport is injected: ``transport(method, url, body_json|None,
headers) -> (status_code, response_json)``.  Production wires an
authenticated session (google-auth + requests); tests replay recorded
responses and assert on the exact requests.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional

BASE = "https://tpu.googleapis.com/v2"

# queuedResource.state.state -> provider tri-state
_STATE_MAP = {
    "CREATING": "PENDING",
    "ACCEPTED": "PENDING",
    "WAITING_FOR_RESOURCES": "PENDING",
    "PROVISIONING": "PENDING",
    "ACTIVE": "ACTIVE",
    "FAILED": "FAILED",
    "SUSPENDED": "FAILED",
    "SUSPENDING": "FAILED",
    "DELETING": "FAILED",
}


class GkeTpuApiError(RuntimeError):
    def __init__(self, status: int, body):
        super().__init__(f"Cloud TPU API error {status}: {body}")
        self.status = status


class GkeQueuedResourceAPI:
    """Speaks the TPUPodProvider client contract over the real REST
    schema (create/get/delete/list + per-host endpoints)."""

    def __init__(self, project: str, zone: str,
                 transport: Callable,
                 token_supplier: Optional[Callable[[], str]] = None,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 enable_external_ips: bool = False,
                 spot: bool = False):
        self.project = project
        self.zone = zone
        self.transport = transport
        self.token_supplier = token_supplier
        self.runtime_version = runtime_version
        self.enable_external_ips = enable_external_ips
        self.spot = spot

    # ---------------------------------------------------------- plumbing
    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        # Re-read per call (tokens rotate); an empty token means "not
        # yet available" and the header is omitted rather than sending
        # a malformed Bearer.
        tok = self.token_supplier() if self.token_supplier else ""
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    def _call(self, method: str, path: str, body: Optional[Dict] = None,
              ok_missing: bool = False):
        url = f"{BASE}/{path}"
        status, resp = self.transport(method, url, body, self._headers())
        if status == 404 and ok_missing:
            raise KeyError(path)
        if status >= 400:
            raise GkeTpuApiError(status, resp)
        return resp

    # ---------------------------------------------------- provider verbs
    def create_queued_resource(self, name: str, accelerator_type: str,
                               hosts: int) -> None:
        """One queued resource = one slice = ONE node whose
        networkEndpoints will carry ``hosts`` entries; the accelerator
        type (e.g. v5litepod-16 = 4 hosts) determines the host count on
        the service side — ``hosts`` is validated against it by the
        service, not resent."""
        node: Dict = {
            "acceleratorType": accelerator_type,
            "runtimeVersion": self.runtime_version,
            "networkConfig": {
                "enableExternalIps": self.enable_external_ips},
        }
        body: Dict = {
            "tpu": {"nodeSpec": [{
                "parent": self._parent,
                "nodeId": f"{name}-node",
                "node": node,
            }]},
        }
        if self.spot:
            body["spot"] = {}
        self._call("POST",
                   f"{self._parent}/queuedResources"
                   f"?queuedResourceId={name}", body)

    def get_queued_resource(self, name: str) -> Dict:
        qr = self._call(
            "GET", f"{self._parent}/queuedResources/{name}",
            ok_missing=True)
        raw_state = (qr.get("state") or {}).get("state", "CREATING")
        state = _STATE_MAP.get(raw_state, "PENDING")
        hosts: List[Dict] = []
        if state == "ACTIVE":
            for spec in (qr.get("tpu") or {}).get("nodeSpec", []):
                node_id = spec["nodeId"]
                node = self._call(
                    "GET", f"{self._parent}/nodes/{node_id}",
                    ok_missing=True)
                for i, ep in enumerate(node.get("networkEndpoints", [])):
                    hosts.append({"id": f"{node_id}-{i}",
                                  "ip": ep.get("ipAddress")})
        return {"state": state, "hosts": hosts, "raw_state": raw_state}

    def delete_queued_resource(self, name: str) -> None:
        # force=true also tears down a granted slice's node (the
        # two-step suspend+delete dance collapsed, as the autoscaler's
        # terminate path expects).
        try:
            self._call("DELETE",
                       f"{self._parent}/queuedResources/{name}"
                       f"?force=true", ok_missing=True)
        except KeyError:
            pass  # already gone: terminate must be idempotent

    def list_queued_resources(self) -> List[str]:
        resp = self._call("GET", f"{self._parent}/queuedResources")
        return [qr["name"].rsplit("/", 1)[-1]
                for qr in resp.get("queuedResources", [])]


def requests_transport(session=None):
    """Production transport over ``requests`` (not used in tests; the
    image has requests but no GCP credentials or egress)."""
    import requests as _requests
    sess = session or _requests.Session()

    def _t(method, url, body, headers):
        r = sess.request(method, url, headers=headers,
                         data=None if body is None else json.dumps(body),
                         timeout=60)
        try:
            payload = r.json()
        except ValueError:
            payload = {"text": r.text}
        return r.status_code, payload

    return _t
