"""TPUPodProvider: TPU slices via a GKE/GCE queued-resources-style API.

Reference role: the cloud providers under
python/ray/autoscaler/_private/{aws,gcp}/node_provider.py — translated
to the TPU acquisition model (SURVEY §7 phase 9): capacity arrives as
whole SLICES through a queued-resource request that is pending until
granted, every host of a slice joins the cluster together, and releasing
any host releases the slice.  The API client is injected so the provider
is unit-testable against a mock; a real deployment passes a thin wrapper
over google-cloud-tpu's QueuedResource RPCs (not importable in this
image, and deliberately out of tree).

API client contract (duck-typed):
  create_queued_resource(name, accelerator_type, hosts) -> None
  get_queued_resource(name) -> {"state": PENDING|ACTIVE|FAILED,
                                "hosts": [{"id", "ip"}, ...]}
  delete_queued_resource(name) -> None
  list_queued_resources() -> [name, ...]
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

PENDING = "PENDING"
ACTIVE = "ACTIVE"
FAILED = "FAILED"


class TPUPodProvider(NodeProvider):
    def __init__(self, node_types: Dict[str, Dict], project: str,
                 zone: str, api=None, gcs_addr=None,
                 bootstrap_runner_factory=None):
        """bootstrap_runner_factory(host_ip) -> command runner used to
        `rt start --address` each granted host (reference: updater+
        command_runner bootstrap of freshly launched cloud nodes)."""
        super().__init__(node_types)
        if api is None:
            raise ValueError(
                "TPUPodProvider needs a queued-resources API client "
                "(inject the google-cloud-tpu wrapper, or a mock)")
        self.api = api
        self.project = project
        self.zone = zone
        self.gcs_addr = gcs_addr
        self.bootstrap_runner_factory = bootstrap_runner_factory
        # queued-resource name -> {"node_type", "group_id", "bootstrapped"}
        self._slices: Dict[str, Dict] = {}

    # ------------------------------------------------------------ verbs
    def create_nodes(self, node_type: str, count: int) -> List[str]:
        spec = self.node_types[node_type]
        hosts = int(spec.get("group_size", 1))
        accel = spec.get("node_config", {}).get("accelerator_type",
                                                "v5litepod-8")
        created = []
        for _ in range(count):
            name = f"rt-{node_type}-{uuid.uuid4().hex[:8]}"
            self.api.create_queued_resource(name, accel, hosts)
            self._slices[name] = {"node_type": node_type,
                                  "group_id": name,
                                  "bootstrapped": set()}
            created.append(name)
        return created

    def non_terminated_nodes(self) -> List[Dict]:
        """ACTIVE slices' hosts (each host = one cluster node).  PENDING
        slices are still queued at the provider; FAILED ones are
        reaped.  Newly ACTIVE hosts get bootstrapped exactly once."""
        out = []
        for name, info in list(self._slices.items()):
            try:
                qr = self.api.get_queued_resource(name)
            except KeyError:
                del self._slices[name]
                continue
            if qr["state"] == FAILED:
                # Grant failed: drop the request so the autoscaler can
                # re-launch (reference: failed node cleanup).
                try:
                    self.api.delete_queued_resource(name)
                except KeyError:
                    pass
                del self._slices[name]
                continue
            if qr["state"] != ACTIVE:
                continue  # still queued: contributes no capacity yet
            for host in qr["hosts"]:
                self._maybe_bootstrap(name, info, host)
                out.append({
                    "provider_id": f"{name}/{host['id']}",
                    "node_type": info["node_type"],
                    "group_id": name,
                    "host_ip": host.get("ip"),
                    # Joined raylets report node ids tagged with the
                    # provider id via RT_NODE_LABEL (idle matching).
                    "raylet_node_id": host.get("raylet_node_id", ""),
                })
        return out

    def _maybe_bootstrap(self, name: str, info: Dict, host: Dict):
        if (self.bootstrap_runner_factory is None
                or host["id"] in info["bootstrapped"]):
            return
        runner = self.bootstrap_runner_factory(host.get("ip"))
        if self.gcs_addr is not None:
            runner.run(f"rt start --address "
                       f"{self.gcs_addr[0]}:{self.gcs_addr[1]} "
                       f"--node-ip {host.get('ip')}")
        info["bootstrapped"].add(host["id"])

    def terminate_node(self, provider_node_id: str) -> None:
        """Atomic slice release: terminating ANY host deletes the whole
        queued resource."""
        name = provider_node_id.split("/", 1)[0]
        if name in self._slices:
            try:
                self.api.delete_queued_resource(name)
            except KeyError:
                pass
            del self._slices[name]


class MockQueuedResourceAPI:
    """Test double simulating the queued-resources lifecycle: requests
    sit PENDING for `grant_after` polls, then become ACTIVE with one
    mock host per requested count (or FAILED if exhausted)."""

    def __init__(self, grant_after: int = 2, capacity_slices: int = 100):
        self.grant_after = grant_after
        self.capacity = capacity_slices
        self._requests: Dict[str, Dict] = {}

    def create_queued_resource(self, name, accelerator_type, hosts):
        if name in self._requests:
            raise ValueError(f"duplicate queued resource {name}")
        will_fail = len([r for r in self._requests.values()
                         if r["state"] != FAILED]) >= self.capacity
        self._requests[name] = {
            "accelerator_type": accelerator_type,
            "hosts_requested": hosts,
            "polls": 0,
            "state": FAILED if will_fail else PENDING,
            "hosts": [],
        }

    def get_queued_resource(self, name):
        req = self._requests.get(name)
        if req is None:
            raise KeyError(name)
        if req["state"] == PENDING:
            req["polls"] += 1
            if req["polls"] >= self.grant_after:
                req["state"] = ACTIVE
                req["hosts"] = [
                    {"id": f"host-{i}", "ip": f"10.0.0.{i + 1}"}
                    for i in range(req["hosts_requested"])]
        return {"state": req["state"], "hosts": list(req["hosts"])}

    def delete_queued_resource(self, name):
        if name not in self._requests:
            raise KeyError(name)
        del self._requests[name]

    def list_queued_resources(self):
        return list(self._requests)
