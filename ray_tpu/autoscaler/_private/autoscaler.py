"""StandardAutoscaler: demand-driven cluster scaling.

Reference: python/ray/autoscaler/_private/autoscaler.py:154 (update :346)
+ resource_demand_scheduler.py:141 (get_nodes_to_launch bin-packing) +
load_metrics.py.  Each update(): read demand from the GCS (queued lease
shapes + unplaced PG bundles), bin-pack what doesn't fit on current
capacity onto node types, launch; terminate nodes idle past the timeout.
TPU slices (group_size > 1) launch and terminate atomically.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


def _fits(avail: Dict, shape: Dict) -> bool:
    return all(avail.get(k, 0) >= v for k, v in shape.items())


def _subtract(avail: Dict, shape: Dict) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0) - v


class StandardAutoscaler:
    def __init__(self, provider: NodeProvider, gcs_request,
                 idle_timeout_s: float = 60.0,
                 max_launch_batch: int = 8):
        """gcs_request: callable(method, body) -> reply (sync)."""
        self.provider = provider
        self.gcs_request = gcs_request
        self.idle_timeout_s = idle_timeout_s
        self.max_launch_batch = max_launch_batch
        self._idle_since: Dict[str, float] = {}  # provider_id -> ts

    # ------------------------------------------------------------- update
    def update(self) -> Dict:
        demands = self._collect_demands()
        nodes = self.gcs_request("get_nodes", {})
        launched = self._scale_up(demands, nodes)
        terminated = self._scale_down(nodes)
        return {"launched": launched, "terminated": terminated,
                "pending_demands": len(demands)}

    def _collect_demands(self) -> List[Dict]:
        reply = self.gcs_request("get_resource_demands", {})
        demands = list(reply.get("shapes", []))
        for pg in reply.get("pending_pgs", []):
            # Each unplaced bundle is a demand; STRICT_SPREAD bundles must
            # land on distinct nodes, which bin-packing below honors by
            # tagging them anti-affine.
            strict_spread = pg.get("strategy") == "STRICT_SPREAD"
            for b in pg["bundles"]:
                d = dict(b)
                if strict_spread:
                    d["__anti_affinity__"] = pg["pg_id"]
                demands.append(d)
        return demands

    def _scale_up(self, demands: List[Dict], nodes) -> List[str]:
        if not demands:
            return []
        # Current free capacity per node (demand already running is
        # reflected in `available`).
        capacity = [dict(n.get("available", {}))
                    for n in nodes if n.get("alive")]
        anti_used: Dict[Tuple, set] = {}
        unmet: List[Dict] = []
        for d in demands:
            anti = d.pop("__anti_affinity__", None)
            placed = False
            for i, cap in enumerate(capacity):
                if anti is not None and i in anti_used.get(anti, set()):
                    continue
                if _fits(cap, d):
                    _subtract(cap, d)
                    if anti is not None:
                        anti_used.setdefault(anti, set()).add(i)
                    placed = True
                    break
            if not placed:
                unmet.append(dict(d, __anti_affinity__=anti)
                             if anti is not None else d)
        if not unmet:
            return []
        # Bin-pack unmet demand onto new virtual nodes of each type
        # (first type whose resources cover the shape; reference:
        # resource_demand_scheduler get_nodes_to_launch).
        live_by_type: Dict[str, int] = {}
        for pn in self.provider.non_terminated_nodes():
            live_by_type[pn["node_type"]] = \
                live_by_type.get(pn["node_type"], 0) + 1
        to_launch: Dict[str, int] = {}
        new_nodes: List[Tuple[str, Dict]] = []  # (type, remaining capacity)
        new_anti: Dict[Tuple, set] = {}
        for d in unmet:
            anti = d.pop("__anti_affinity__", None)
            placed = False
            for j, (ntype, cap) in enumerate(new_nodes):
                if anti is not None and j in new_anti.get(anti, set()):
                    continue
                if _fits(cap, d):
                    _subtract(cap, d)
                    if anti is not None:
                        new_anti.setdefault(anti, set()).add(j)
                    placed = True
                    break
            if placed:
                continue
            for ntype, spec in self.provider.node_types.items():
                group = int(spec.get("group_size", 1))
                live_groups = live_by_type.get(ntype, 0) // group
                if live_groups + to_launch.get(ntype, 0) + 1 \
                        > spec.get("max_workers", 2 ** 30):
                    continue
                node_res = dict(spec["resources"])
                if _fits(node_res, d):
                    _subtract(node_res, d)
                    idx = len(new_nodes)
                    new_nodes.append((ntype, node_res))
                    # A slice contributes group_size hosts of capacity.
                    for _ in range(group - 1):
                        new_nodes.append((ntype,
                                          dict(spec["resources"])))
                    if anti is not None:
                        new_anti.setdefault(anti, set()).add(idx)
                    to_launch[ntype] = to_launch.get(ntype, 0) + 1
                    break
            else:
                logger.warning("autoscaler: demand %s unsatisfiable by "
                               "any node type", d)
        launched = []
        for ntype, count in to_launch.items():
            count = min(count, self.max_launch_batch)
            logger.info("autoscaler: launching %d x %s", count, ntype)
            launched += self.provider.create_nodes(ntype, count)
        return launched

    def _scale_down(self, nodes) -> List[str]:
        """Terminate provider node GROUPS that are wholly idle past
        idle_timeout_s.  Per-group, not per-host: terminating one host of
        a TPU slice tears down the whole slice, so a group with ANY busy
        host must be left alone."""
        now = time.monotonic()
        by_raylet_id = {}
        for n in nodes:
            by_raylet_id[n["node_id"].hex()] = n

        def _host_idle(pn) -> bool:
            view = by_raylet_id.get(pn.get("raylet_node_id", ""))
            if view is None or not view.get("alive"):
                return False
            total = view.get("resources", {})
            avail = view.get("available", {})
            return (view.get("load", 0) == 0
                    and all(avail.get(k, 0) >= v
                            for k, v in total.items()))

        groups: Dict[str, List[Dict]] = {}
        for pn in self.provider.non_terminated_nodes():
            groups.setdefault(pn.get("group_id", pn["provider_id"]),
                              []).append(pn)
        terminated = []
        for gid, members in groups.items():
            if not all(_host_idle(pn) for pn in members):
                self._idle_since.pop(gid, None)
                continue
            first = self._idle_since.setdefault(gid, now)
            if now - first >= self.idle_timeout_s:
                pid = members[0]["provider_id"]
                logger.info("autoscaler: terminating idle group %s "
                            "(%d host(s))", gid, len(members))
                # Providers tear down the whole group atomically.
                self.provider.terminate_node(pid)
                self._idle_since.pop(gid, None)
                terminated.append(gid)
        return terminated


class Monitor:
    """Drives autoscaler.update() on an interval (reference:
    autoscaler/_private/monitor.py)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 1.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        import threading
        # An Event, not a bare bool: stop() must interrupt the sleep
        # (a bool left the thread parked for a full interval, and a
        # long interval outlived stop()'s bounded join).
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        import threading
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler-monitor")
        self._thread.start()

    def _run(self):
        while True:
            try:
                self.autoscaler.update()
            except Exception:
                logger.exception("autoscaler update failed")
            if self._stop.wait(self.interval_s):
                return

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
