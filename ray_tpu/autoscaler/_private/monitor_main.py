"""Autoscaler monitor process: `rt up` launches this on the head.

Reference: python/ray/autoscaler/_private/monitor.py — a standalone
process polling the GCS for resource demand and driving
StandardAutoscaler.update() on an interval.  It also persists the pids
of provider-launched node processes into the cluster state file so
`rt down` can tear the whole cluster down without this process.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time

logger = logging.getLogger("rt-autoscaler-monitor")


def _persist_worker_pids(state_file: str, provider) -> None:
    try:
        with open(state_file) as f:
            state = json.load(f)
    except (OSError, ValueError):
        state = {}
    pids = {}
    for info in getattr(provider, "_nodes", {}).values():
        node = info.get("node")
        if node is None:
            continue
        for role, pid in node.pids().items():
            pids[f"{role}:{pid}"] = pid
    state["worker_pids"] = sorted(set(pids.values()))
    tmp = state_file + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=2)
    os.replace(tmp, state_file)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("config_file")
    p.add_argument("--gcs", required=True, help="host:port")
    p.add_argument("--state-file", required=True)
    p.add_argument("--interval", type=float, default=2.0)
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="[monitor] %(levelname)s %(message)s")
    from ray_tpu.autoscaler import StandardAutoscaler
    from ray_tpu.autoscaler.config import (load_cluster_config,
                                           min_worker_demands,
                                           provider_from_config)
    config = load_cluster_config(args.config_file)
    host, port = args.gcs.rsplit(":", 1)
    gcs_addr = (host, int(port))

    import ray_tpu
    ray_tpu.init(address=args.gcs)
    from ray_tpu._private import worker as worker_mod

    def gcs_request(method, body):
        w = worker_mod.global_worker
        return w._run(w._gcs_request(method, body))

    provider = provider_from_config(config, gcs_addr=gcs_addr)
    autoscaler = StandardAutoscaler(
        provider, gcs_request,
        idle_timeout_s=config["idle_timeout_minutes"] * 60.0)

    # Bring up min_workers before demand exists (reference:
    # ResourceDemandScheduler treats min_workers as standing demand).
    for name, nt in config["available_node_types"].items():
        want = nt.get("min_workers", 0)
        have = len([n for n in provider.non_terminated_nodes()
                    if n["node_type"] == name]) // nt.get("group_size", 1)
        if want > have:
            logger.info("launching %d min_workers of %s", want - have,
                        name)
            provider.create_nodes(name, want - have)
    _persist_worker_pids(args.state_file, provider)

    while True:
        try:
            autoscaler.update()
            _persist_worker_pids(args.state_file, provider)
        except Exception:
            logger.exception("autoscaler update failed")
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
