"""Cluster YAML config: schema validation + defaults.

Reference: python/ray/autoscaler/ray-schema.json (the validated cluster
launch YAML) and _private/util.py prepare_config/validate_config.  The
shape mirrors the reference's: provider block, available_node_types with
per-type resources/min/max, head node, idle timeout, and bootstrap
commands run through the command runner.
"""

from __future__ import annotations

from typing import Dict, List

_PROVIDER_TYPES = ("local_process", "tpu_pod", "fake")


class ClusterConfigError(ValueError):
    pass


_TOP_KEYS = {
    "cluster_name": str,
    "max_workers": int,
    "idle_timeout_minutes": (int, float),
    "provider": dict,
    "head_node": dict,
    "available_node_types": dict,
    "setup_commands": list,
    "head_setup_commands": list,
    "worker_setup_commands": list,
    "head_start_command": str,
    "worker_start_command": str,
}

_NODE_TYPE_KEYS = {
    "resources": dict,
    "min_workers": int,
    "max_workers": int,
    "group_size": int,
    "node_config": dict,
}


def validate_cluster_config(config: Dict) -> Dict:
    """Validate and apply defaults; returns a new normalized config."""
    if not isinstance(config, dict):
        raise ClusterConfigError("cluster config must be a mapping")
    for key, value in config.items():
        expected = _TOP_KEYS.get(key)
        if expected is None:
            raise ClusterConfigError(
                f"unknown cluster config key {key!r}; valid: "
                f"{sorted(_TOP_KEYS)}")
        if not isinstance(value, expected):
            raise ClusterConfigError(
                f"{key} must be {expected}, got {type(value).__name__}")
    out = dict(config)
    out.setdefault("cluster_name", "default")
    out.setdefault("max_workers", 8)
    out.setdefault("idle_timeout_minutes", 5)
    provider = out.get("provider")
    if not provider or "type" not in provider:
        raise ClusterConfigError("config needs provider: {type: ...}")
    if provider["type"] not in _PROVIDER_TYPES:
        raise ClusterConfigError(
            f"provider.type {provider['type']!r} not one of "
            f"{_PROVIDER_TYPES}")
    node_types = out.get("available_node_types")
    if not node_types:
        raise ClusterConfigError("config needs available_node_types")
    for name, nt in node_types.items():
        if not isinstance(nt, dict):
            raise ClusterConfigError(
                f"available_node_types.{name} must be a mapping")
        for key, value in nt.items():
            expected = _NODE_TYPE_KEYS.get(key)
            if expected is None:
                raise ClusterConfigError(
                    f"available_node_types.{name} has unknown key "
                    f"{key!r}; valid: {sorted(_NODE_TYPE_KEYS)}")
            if not isinstance(value, expected):
                raise ClusterConfigError(
                    f"available_node_types.{name}.{key} must be "
                    f"{expected}")
        if "resources" not in nt:
            raise ClusterConfigError(
                f"available_node_types.{name} needs resources")
        nt.setdefault("min_workers", 0)
        nt.setdefault("max_workers", out["max_workers"])
        nt.setdefault("group_size", 1)
    out.setdefault("head_node", {"resources": {"CPU": 1}})
    out["head_node"].setdefault("resources", {"CPU": 1})
    out.setdefault("setup_commands", [])
    return out


def load_cluster_config(path: str) -> Dict:
    import yaml
    with open(path) as f:
        return validate_cluster_config(yaml.safe_load(f))


def provider_from_config(config: Dict, gcs_addr=None,
                         session_dir=None):
    """Instantiate the provider named by the config (the reference's
    _get_node_provider registry, node_provider.py:_NODE_PROVIDERS)."""
    ptype = config["provider"]["type"]
    node_types = config["available_node_types"]
    if ptype == "local_process":
        from ray_tpu.autoscaler.node_provider import (
            LocalProcessNodeProvider)
        if gcs_addr is None:
            raise ClusterConfigError(
                "local_process provider needs the head GCS address")
        return LocalProcessNodeProvider(node_types, gcs_addr=gcs_addr,
                                        session_dir=session_dir)
    if ptype == "tpu_pod":
        from ray_tpu.autoscaler.tpu_pod_provider import (
            MockQueuedResourceAPI, TPUPodProvider)
        pconf = config["provider"]
        api_kind = pconf.get("api", "gke")
        if api_kind == "mock":
            api = MockQueuedResourceAPI()
        else:
            # The real Cloud TPU v2 REST client; only the transport
            # would differ in a recorded-response test.
            import os

            from ray_tpu.autoscaler.gke_tpu_api import (
                GkeQueuedResourceAPI, requests_transport)
            # Read per call — GCP access tokens expire (~1h); a
            # rotation (or a first export after startup) just updates
            # the env var, so the supplier is unconditional and the
            # header is simply omitted while the var is empty.
            api = GkeQueuedResourceAPI(
                pconf.get("project", ""), pconf.get("zone", ""),
                requests_transport(),
                token_supplier=lambda: os.environ.get("RT_GCP_TOKEN", ""),
                runtime_version=pconf.get("runtime_version",
                                          "tpu-ubuntu2204-base"),
                spot=bool(pconf.get("spot", False)))
        return TPUPodProvider(node_types,
                              pconf.get("project", ""),
                              pconf.get("zone", ""),
                              api=api,
                              gcs_addr=gcs_addr)
    raise ClusterConfigError(
        f"provider {ptype!r} must be created by the test harness")


def min_worker_demands(config: Dict) -> List[Dict]:
    """Synthetic demand shapes that force min_workers of each type up
    (reference: ResourceDemandScheduler's min_workers handling)."""
    demands = []
    for name, nt in config["available_node_types"].items():
        for _ in range(nt.get("min_workers", 0)):
            demands.append(dict(nt["resources"]))
    return demands
