"""NodeProvider: the pluggable boundary between scaling logic and
infrastructure.

Reference: python/ray/autoscaler/node_provider.py (NodeProvider base) and
_private/fake_multi_node/node_provider.py:237 (FakeMultiNodeProvider —
"launches" are local raylets, so autoscaling logic is testable without a
cloud).  TPU detail: a node type may declare group_size > 1, modeling a
multi-host TPU slice that must be acquired and released atomically.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Subclass per infrastructure (GKE queued resources, GCE, fake)."""

    def __init__(self, node_types: Dict[str, Dict]):
        # node_types: name -> {"resources": {...}, "max_workers": int,
        #                      "group_size": int (default 1), ...}
        self.node_types = node_types

    def non_terminated_nodes(self) -> List[Dict]:
        """[{node_id, node_type, group_id}] of live provider nodes."""
        raise NotImplementedError

    def create_nodes(self, node_type: str, count: int) -> List[str]:
        """Launch count nodes (each group_size hosts) of node_type."""
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches in-process raylets on the test Cluster (reference:
    fake_multi_node/node_provider.py:237)."""

    def __init__(self, node_types: Dict[str, Dict], cluster):
        super().__init__(node_types)
        self.cluster = cluster
        self._nodes: Dict[str, Dict] = {}

    def non_terminated_nodes(self) -> List[Dict]:
        return [dict(v, provider_id=k) for k, v in self._nodes.items()]

    def create_nodes(self, node_type: str, count: int) -> List[str]:
        spec = self.node_types[node_type]
        group_size = int(spec.get("group_size", 1))
        created = []
        for _ in range(count):
            group_id = uuid.uuid4().hex[:8]
            for _host in range(group_size):
                node = self.cluster.add_node(
                    num_cpus=spec["resources"].get("CPU", 1),
                    resources={k: v for k, v in spec["resources"].items()
                               if k != "CPU"})
                pid = uuid.uuid4().hex[:8]
                self._nodes[pid] = {"node_type": node_type,
                                    "group_id": group_id,
                                    "node": node,
                                    "raylet_node_id":
                                        node.raylet.node_id.hex()}
                created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        info = self._nodes.pop(provider_node_id, None)
        if info is None:
            return
        # Atomic slice teardown: losing one host kills the whole group.
        group = [k for k, v in self._nodes.items()
                 if v["group_id"] == info["group_id"]]
        self.cluster.remove_node(info["node"])
        for k in group:
            peer = self._nodes.pop(k)
            self.cluster.remove_node(peer["node"])


class LocalProcessNodeProvider(NodeProvider):
    """Launches REAL raylet OS processes joined to a running GCS — the
    provider the autoscaler e2e tests use now that the process topology
    exists (reference role: fake_multi_node's docker-compose variant,
    test_utils.py — one real process group per provider node).  A cloud
    provider (GKE queued-resources etc.) implements the same 3 verbs
    with its API instead of subprocess."""

    def __init__(self, node_types: Dict[str, Dict], gcs_addr,
                 session_dir: str | None = None,
                 object_store_memory: int = 128 * 1024 * 1024):
        super().__init__(node_types)
        self.gcs_addr = tuple(gcs_addr)
        self.session_dir = session_dir
        self.object_store_memory = object_store_memory
        self._nodes: Dict[str, Dict] = {}

    def non_terminated_nodes(self) -> List[Dict]:
        out = []
        for k, v in list(self._nodes.items()):
            if k not in self._nodes:
                continue  # reaped as a dead host's group peer below
            if v["node"].raylet_proc.poll() is not None:
                # Process died out from under us: atomic-slice contract —
                # tear down the whole group, same as terminate_node.
                self._nodes.pop(k, None)
                for peer_key in [pk for pk, pv in self._nodes.items()
                                 if pv["group_id"] == v["group_id"]]:
                    self._nodes.pop(peer_key)["node"].kill_raylet()
                continue
            out.append(dict(v, provider_id=k))
        return out

    def create_nodes(self, node_type: str, count: int) -> List[str]:
        from ray_tpu._private.node import NodeProcesses, new_session_dir
        spec = self.node_types[node_type]
        group_size = int(spec.get("group_size", 1))
        created = []
        for _ in range(count):
            group_id = uuid.uuid4().hex[:8]
            for _host in range(group_size):
                node = NodeProcesses(
                    session_dir=self.session_dir or new_session_dir(),
                    head=False, gcs_addr=self.gcs_addr,
                    num_cpus=spec["resources"].get("CPU", 1),
                    resources={k: v for k, v in spec["resources"].items()
                               if k != "CPU"},
                    object_store_memory=self.object_store_memory,
                ).start()
                pid = uuid.uuid4().hex[:8]
                self._nodes[pid] = {"node_type": node_type,
                                    "group_id": group_id,
                                    "node": node,
                                    # Idle-drain matching key in
                                    # StandardAutoscaler._scale_down.
                                    "raylet_node_id": node.raylet_node_id}
                created.append(pid)
        return created

    def terminate_node(self, provider_node_id: str) -> None:
        info = self._nodes.pop(provider_node_id, None)
        if info is None:
            return
        group = [k for k, v in self._nodes.items()
                 if v["group_id"] == info["group_id"]]
        info["node"].kill_raylet()
        for k in group:
            self._nodes.pop(k)["node"].kill_raylet()
