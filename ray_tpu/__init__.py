"""ray_tpu: a TPU-native distributed computing framework.

Tasks, actors, and a shared-memory object store coordinated by a global
control service (GCS) and per-node raylets, with ML libraries on top —
distributed training driving jax pjit/shard_map SPMD over TPU meshes,
hyperparameter tuning, serving, datasets, and RL.  Capabilities mirror the
reference (justinvyu/ray, surveyed in SURVEY.md); the accelerator substrate
is TPU-first throughout: TPU chips/slices/ICI topology are first-class
scheduler resources and collectives are XLA over ICI/DCN rather than NCCL.
"""

__version__ = "0.1.0"

from ray_tpu._private.api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    cluster_trace,
    get,
    get_trace,
    get_actor,
    get_gpu_ids,
    get_runtime_context,
    get_tpu_ids,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
    wait_placement_group_ready,
)
from ray_tpu._private.object_ref import (  # noqa: F401
    ObjectRef,
    ObjectRefGenerator,
)
from ray_tpu.actor import method  # noqa: F401
from ray_tpu import exceptions  # noqa: F401

from ray_tpu.exceptions import (  # noqa: F401
    ActorDiedError,
    TaskCancelledError,
    ActorError,
    GetTimeoutError,
    ObjectLostError,
    RayTpuError,
    TaskError,
)

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "cancel",
    "kill", "get_actor", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "get_tpu_ids",
    "get_gpu_ids", "ObjectRef", "ObjectRefGenerator", "method",
    "exceptions", "__version__",
]
