"""Runtime environments: per-task/actor execution environments.

Reference: python/ray/runtime_env/runtime_env.py (RuntimeEnv) +
_private/runtime_env/{working_dir,py_modules,pip}.py — working_dir/
py_modules are content-addressed packages uploaded once (URI-cached,
packaging.py) and materialized on workers; env_vars apply to the
executing worker; `pip` gives the task a DEDICATED worker running in a
content-addressed virtualenv (pip-spec hash -> cached venv, reference
pip.py) so two tasks in one cluster can import different versions of the
same package; `conda` runs the worker under an existing conda env's
interpreter (reference: _private/runtime_env/conda.py); `container`
runs the worker INSIDE an OCI image via podman/docker with the session
dir bind-mounted, so the shm-store mmap stays zero-copy (reference:
_private/runtime_env/container.py).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Dict, List, Optional, Union

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "conda",
              "container"}
_MAX_PACKAGE_BYTES = 100 * 1024 * 1024


class RuntimeEnv(dict):
    def __init__(self, *, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None,
                 py_modules: Optional[List[str]] = None,
                 pip: Optional[List[str]] = None,
                 conda: Optional[Union[str, Dict]] = None,
                 container: Optional[Dict] = None, **extra):
        unsupported = set(extra) - _SUPPORTED
        if unsupported:
            raise ValueError(
                f"unsupported runtime_env fields {sorted(unsupported)} "
                f"(supported: {sorted(_SUPPORTED)})")
        super().__init__()
        if env_vars:
            self["env_vars"] = dict(env_vars)
        if working_dir:
            self["working_dir"] = working_dir
        if py_modules:
            self["py_modules"] = list(py_modules)
        if pip:
            self["pip"] = [str(p) for p in pip]
        if conda:
            if not isinstance(conda, str):
                raise ValueError(
                    "conda runtime_env takes an existing env NAME or "
                    "prefix path (creating envs from a spec dict is "
                    "not supported — prebuild the env)")
            self["conda"] = conda
        if container:
            if not isinstance(container, dict) \
                    or not container.get("image"):
                raise ValueError(
                    'container runtime_env needs {"image": ..., '
                    '"run_options": [...]}')
            self["container"] = {
                "image": str(container["image"]),
                "run_options": [str(o) for o in
                                container.get("run_options", [])],
            }
        exclusive = [k for k in ("pip", "conda", "container") if k in self]
        if len(exclusive) > 1:
            raise ValueError(
                f"runtime_env fields {exclusive} are mutually exclusive "
                "(each selects the worker's interpreter environment)")


def _canonical_conda(spec) -> str:
    """Canonicalize a conda spec PURELY SYNTACTICALLY so the pool key
    is identical on every host: the env given by name ('myenv') and by
    a standard-layout prefix ('<root>/envs/myenv') resolve to the same
    interpreter in the raylet (_spawn_conda_worker) and must share one
    warm-worker pool.  No filesystem or CONDA_* lookups here — the key
    is computed on both the driver and the raylet, which may not share
    a conda install; only the raylet resolves name -> interpreter."""
    spec = str(spec)
    if os.sep in spec:
        path = os.path.normpath(spec)
        if os.path.basename(os.path.dirname(path)) == "envs":
            return os.path.basename(path)  # <root>/envs/<name> -> name
        return path  # non-standard prefix: key on the path itself
    return spec


def worker_env_key(runtime_env: Optional[dict]) -> str:
    """Content address of the worker-interpreter environment ('' = the
    base interpreter).  Workers are pooled per key: a task only ever
    runs on a worker whose pip venv / conda env / container image
    matches (reference: the worker-pool runtime-env hash in
    worker_pool.h PopWorker)."""
    if not runtime_env:
        return ""
    parts = []
    if runtime_env.get("pip"):
        parts.append("pip:" + "\n".join(sorted(runtime_env["pip"])))
    if runtime_env.get("conda"):
        parts.append("conda:" + _canonical_conda(runtime_env["conda"]))
    if runtime_env.get("container"):
        parts.append("container:" + json.dumps(runtime_env["container"],
                                               sort_keys=True))
    if not parts:
        return ""
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


def env_spec(runtime_env: Optional[dict]) -> Optional[dict]:
    """The interpreter-environment subset of a runtime_env (what a
    raylet needs to spawn a matching worker)."""
    if not runtime_env:
        return None
    spec = {k: runtime_env[k] for k in ("pip", "conda", "container")
            if runtime_env.get(k)}
    return spec or None


# Back-compat alias (pre-conda/container name).
pip_env_key = worker_env_key


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, _dirs, files in os.walk(path):
            for name in files:
                if name.endswith(".pyc") or "__pycache__" in root:
                    continue
                full = os.path.join(root, name)
                z.write(full, os.path.relpath(full, path))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES})")
    return data


def pack(runtime_env: Optional[dict], gcs_kv_put) -> Optional[dict]:
    """Driver side: upload directory packages to the GCS KV under their
    content hash (reference: packaging.py upload_package_if_needed);
    returns the wire form with gcs:// URIs."""
    if not runtime_env:
        return None
    out = dict(runtime_env)
    for field in ("working_dir", "py_modules"):
        val = out.get(field)
        if val is None:
            continue
        paths = [val] if isinstance(val, str) else list(val)
        uris = []
        for p in paths:
            if p.startswith("gcs://"):
                uris.append(p)
                continue
            data = _zip_dir(p)
            digest = hashlib.sha1(data).hexdigest()[:16]
            key = f"pkg_{digest}.zip".encode()
            gcs_kv_put("runtime_env", key, data)
            uris.append(f"gcs://{key.decode()}")
        out[field] = uris[0] if field == "working_dir" else uris
    return out


# Worker-side package cache: uri -> extracted dir.
_materialized: Dict[str, str] = {}


def apply(runtime_env: Optional[dict], gcs_kv_get, cache_dir: str):
    """Worker side: materialize packages + set env vars before executing
    (reference: the runtime-env agent's create flow, minus process
    isolation — packages are cached per URI like uri_cache.py)."""
    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = str(v)

    def _materialize(uri: str) -> str:
        cached = _materialized.get(uri)
        if cached is not None:
            return cached
        key = uri[len("gcs://"):].encode()
        data = gcs_kv_get("runtime_env", key)
        if data is None:
            raise RuntimeError(f"runtime_env package {uri} not found")
        dest = os.path.join(cache_dir, uri[len("gcs://"):-len(".zip")])
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            z.extractall(dest)
        _materialized[uri] = dest
        return dest

    wd = runtime_env.get("working_dir")
    if wd:
        dest = _materialize(wd)
        os.chdir(dest)
        if dest not in sys.path:
            sys.path.insert(0, dest)
    for uri in runtime_env.get("py_modules") or []:
        dest = _materialize(uri)
        if dest not in sys.path:
            sys.path.insert(0, dest)
