"""Model catalog: default flax networks for RL policies.

Reference: rllib/models/catalog.py (ModelCatalog) + the JAX model sketches
the reference started (rllib/models/jax/fcnet.py).  Here jax IS the
framework: models are flax modules jitted into the policy's train step, so
the MXU sees one fused forward/backward per SGD minibatch.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


class FCPolicyValueNet(nn.Module):
    """Shared-trunk MLP with categorical-logits + value heads (reference:
    fcnet defaults - two 256 tanh layers; 64s are plenty for classic
    control)."""

    num_actions: int
    hiddens: Sequence[int] = (64, 64)

    @nn.compact
    def __call__(self, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
        h = x
        for width in self.hiddens:
            h = nn.tanh(nn.Dense(width)(h))
        logits = nn.Dense(self.num_actions)(h)
        value = nn.Dense(1)(h)
        return logits, value[..., 0]
