"""VectorEnv: N same-type envs stepped as a batch.

Reference: rllib/env/vector_env.py — one policy forward serves N envs per
step (`num_envs_per_worker`), amortizing inference over the batch; envs
that finish are reset individually (`reset_at`).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np


class VectorEnv:
    def __init__(self, envs: List):
        assert envs, "need at least one env"
        self.envs = envs
        self.num_envs = len(envs)
        self.observation_space = envs[0].observation_space
        self.action_space = envs[0].action_space

    @classmethod
    def from_creator(cls, creator: Callable, num_envs: int,
                     config=None) -> "VectorEnv":
        return cls([creator(dict(config or {})) for _ in range(num_envs)])

    def vector_reset(self, *, seed: Optional[int] = None):
        obs = []
        for i, env in enumerate(self.envs):
            o, _ = env.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        return np.asarray(obs, np.float32)

    def reset_at(self, index: int):
        o, _ = self.envs[index].reset()
        return np.asarray(o, np.float32)

    def vector_step(self, actions):
        obs, rews, terms, truncs = [], [], [], []
        for env, a in zip(self.envs, actions):
            o, r, te, tr, _ = env.step(a)
            obs.append(o)
            rews.append(float(r))
            terms.append(bool(te))
            truncs.append(bool(tr))
        return (np.asarray(obs, np.float32), np.asarray(rews, np.float32),
                np.asarray(terms), np.asarray(truncs))
