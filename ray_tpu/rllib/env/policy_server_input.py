"""PolicyServerInput: serve a live policy to EXTERNAL simulators over
HTTP and turn their experience into training batches.

Reference: rllib/env/policy_server_input.py:87 + policy_client.py:46 —
an external process (a game server, a robot, a simulator we don't
control) drives episodes through a REST API: it asks the server for
actions and logs rewards; the server executes inference with the
algorithm's current policy and assembles completed episodes into
SampleBatches that training consumes like any rollout.

Wire format: POST <verb> with a pickled dict body; pickled dict reply
(the reference uses pickled payloads over HTTP the same way).  The
server is for trusted, in-deployment simulators — same trust model as
the reference.
"""

from __future__ import annotations

import pickle
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class _Episode:
    def __init__(self):
        self.obs: List[np.ndarray] = []
        self.actions: List = []
        # rewards[i] accumulates ALL log_returns calls between action i
        # and action i+1 (the reference client supports intermediate
        # rewards between get_action calls).
        self.rewards: List[float] = []
        self.logps: List[float] = []
        self.last_touch = time.monotonic()


class PolicyServerInput:
    """HTTP front-end for external-env rollouts.

    `policy_fn` returns the LIVE policy object on every call, so weight
    updates between training iterations are served immediately.
    Completed episodes land in an internal queue; `next()` hands them to
    the training loop (the InputReader contract, reference:
    offline/input_reader.py + policy_server_input.py)."""

    def __init__(self, policy_fn: Callable[[], object],
                 host: str = "127.0.0.1", port: int = 0):
        self._policy_fn = policy_fn
        self._episodes: Dict[str, _Episode] = {}
        self._lock = threading.Lock()
        self._queue: "queue.Queue[SampleBatch]" = queue.Queue()
        self._episode_rewards: List[float] = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = pickle.loads(self.rfile.read(length))
                    reply = outer._dispatch(self.path.strip("/"), body)
                    blob = pickle.dumps({"ok": True, "result": reply})
                    self.send_response(200)
                except Exception as e:  # surfaced client-side
                    blob = pickle.dumps({"ok": False, "error": repr(e)})
                    self.send_response(500)
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.address = (f"http://{self._server.server_address[0]}:"
                        f"{self._server.server_address[1]}")
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    # --------------------------------------------------------- protocol
    _EPISODE_TTL_S = 3600.0
    _MAX_EPISODES = 10_000

    def _gc_episodes_locked(self):
        """Drop abandoned episodes (client crashed before end_episode):
        idle past the TTL, or oldest-first past the cap."""
        now = time.monotonic()
        stale = [eid for eid, ep in self._episodes.items()
                 if now - ep.last_touch > self._EPISODE_TTL_S]
        for eid in stale:
            del self._episodes[eid]
        while len(self._episodes) > self._MAX_EPISODES:
            oldest = min(self._episodes,
                         key=lambda e: self._episodes[e].last_touch)
            del self._episodes[oldest]

    def _episode(self, body) -> _Episode:
        ep = self._episodes.get(body["episode_id"])
        if ep is None:
            raise KeyError(f"unknown episode {body['episode_id']}")
        ep.last_touch = time.monotonic()
        return ep

    def _dispatch(self, verb: str, body: Dict):
        if verb == "start_episode":
            with self._lock:
                self._gc_episodes_locked()
                eid = body.get("episode_id") or uuid.uuid4().hex[:12]
                self._episodes[eid] = _Episode()
            return eid
        if verb == "get_action":
            obs = np.asarray(body["observation"], np.float32)
            with self._lock:
                self._episode(body)  # exists + touch
            # Inference OUTSIDE the lock: concurrent clients must not
            # serialize on each other's forward passes.
            policy = self._policy_fn()
            action, logp, _ = policy.compute_actions(obs[None, :])
            with self._lock:
                ep = self._episode(body)
                ep.obs.append(obs)
                ep.actions.append(action[0])
                ep.logps.append(float(logp[0]))
                ep.rewards.append(0.0)
            return action[0]
        if verb == "log_action":
            # Client-side action (off-policy logging, reference:
            # policy_client.log_action).
            obs = np.asarray(body["observation"], np.float32)
            with self._lock:
                ep = self._episode(body)
                ep.obs.append(obs)
                ep.actions.append(body["action"])
                ep.logps.append(0.0)
                ep.rewards.append(0.0)
            return None
        if verb == "log_returns":
            with self._lock:
                ep = self._episode(body)
                if not ep.rewards:
                    raise ValueError("log_returns before any action")
                ep.rewards[-1] += float(body["reward"])
            return None
        if verb == "end_episode":
            final_obs = np.asarray(body["observation"], np.float32)
            with self._lock:
                self._episode(body)
                ep = self._episodes.pop(body["episode_id"])
                self._episode_rewards.append(float(sum(ep.rewards)))
            batch = self._assemble(ep, final_obs)
            if batch is not None:
                self._queue.put(batch)
            return None
        raise ValueError(f"unknown verb {verb}")

    @staticmethod
    def _assemble(ep: _Episode, final_obs) -> Optional[SampleBatch]:
        n = len(ep.actions)
        if n == 0:
            return None
        rewards = ep.rewards
        new_obs = ep.obs[1:] + [final_obs]
        dones = np.zeros(n, np.bool_)
        dones[-1] = True
        acts = np.asarray(ep.actions)
        if acts.dtype.kind in "iu":
            acts = acts.astype(np.int64)
        else:
            acts = acts.astype(np.float32)
        return SampleBatch({
            "obs": np.asarray(ep.obs, np.float32),
            "actions": acts,
            "rewards": np.asarray(rewards[:n], np.float32),
            "dones": dones,
            "new_obs": np.asarray(new_obs, np.float32),
            "action_logp": np.asarray(ep.logps, np.float32),
            "vf_preds": np.zeros(n, np.float32),
        })

    # ------------------------------------------------------ input reader
    def next(self, timeout: Optional[float] = None
             ) -> Optional[SampleBatch]:
        """The next completed episode (None on timeout)."""
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain_episode_rewards(self) -> List[float]:
        """Completed external episodes' returns since the last call
        (feeds episode_reward_mean)."""
        with self._lock:
            out = self._episode_rewards
            self._episode_rewards = []
        return out

    def try_drain(self) -> List[SampleBatch]:
        out = []
        while True:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                return out

    def shutdown(self):
        self._server.shutdown()
        self._thread.join(timeout=5)
