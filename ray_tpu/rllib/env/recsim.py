"""Toy interest-evolution recommender environment for SlateQ.

Reference: rllib/examples/env/recsim_recommender_system_envs.py (RecSim
"interest evolution" wrapper) — re-built as a dependency-free toy with
the same structure: per-step candidate documents, slate actions, a
conditional-logit user choice model (with a no-click option), engagement
reward on click, and user-interest drift toward consumed content.

Observation = [user_interest (d) | doc features (n_docs * (d+1))] where
each doc row is (topic vector, quality).  Action = a slate: a tuple of
`slate_size` doc indices.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class InterestEvolutionRecSimEnv:
    """Session ends when the user's time budget runs out; higher-quality
    clicks cost less budget, so good recommendations lengthen sessions
    (the long-term value SlateQ is designed to capture)."""

    def __init__(self, config: Optional[Dict] = None):
        config = dict(config or {})
        self.num_docs = int(config.get("num_candidates", 10))
        self.slate_size = int(config.get("slate_size", 2))
        self.topic_dim = int(config.get("topic_dim", 4))
        self.budget0 = float(config.get("time_budget", 20.0))
        self.no_click_logit = float(config.get("no_click_logit", 1.0))
        self._rng = np.random.RandomState(config.get("seed", 0))
        self.observation_dim = (self.topic_dim
                                + self.num_docs * (self.topic_dim + 1))

    def _sample_docs(self):
        topics = self._rng.randn(self.num_docs, self.topic_dim)
        topics /= np.linalg.norm(topics, axis=1, keepdims=True)
        quality = self._rng.uniform(0.0, 1.0, self.num_docs)
        return topics.astype(np.float32), quality.astype(np.float32)

    def _obs(self):
        docs = np.concatenate(
            [self.doc_topics, self.doc_quality[:, None]], axis=1)
        return np.concatenate([self.interest,
                               docs.reshape(-1)]).astype(np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self.interest = self._rng.randn(self.topic_dim).astype(np.float32)
        self.interest /= np.linalg.norm(self.interest)
        self.budget = self.budget0
        self.doc_topics, self.doc_quality = self._sample_docs()
        return self._obs(), {}

    def choice_probs(self, slate) -> np.ndarray:
        """Conditional logit over slate items + no-click (last entry)."""
        scores = np.array([self.interest @ self.doc_topics[i]
                           for i in slate] + [self.no_click_logit])
        e = np.exp(scores - scores.max())
        return e / e.sum()

    def step(self, slate) -> Tuple[np.ndarray, float, bool, bool, Dict]:
        slate = list(slate)
        probs = self.choice_probs(slate)
        pick = self._rng.choice(len(slate) + 1, p=probs)
        reward = 0.0
        info: Dict = {"clicked": None}
        if pick < len(slate):
            doc = slate[pick]
            # Engagement = interest affinity; watching costs budget,
            # discounted by quality (good docs regenerate attention).
            affinity = float(self.interest @ self.doc_topics[doc])
            reward = max(affinity, 0.0) + self.doc_quality[doc]
            self.budget -= 1.0 - 0.5 * self.doc_quality[doc]
            # Interest drifts toward consumed topics.
            self.interest = 0.9 * self.interest \
                + 0.1 * self.doc_topics[doc]
            self.interest /= np.linalg.norm(self.interest)
            info["clicked"] = doc
        else:
            self.budget -= 1.0
        self.doc_topics, self.doc_quality = self._sample_docs()
        done = self.budget <= 0
        return self._obs(), reward, done, False, info
