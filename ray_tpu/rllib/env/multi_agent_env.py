"""Multi-agent environment API.

Reference: rllib/env/multi_agent_env.py — an env whose reset/step speak
per-agent dicts; episode termination is signalled via the "__all__" key.
Agents may come and go between steps (only agents present in the obs dict
act next step).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple


class MultiAgentEnv:
    """Dict-in / dict-out environment.

    reset(seed)  -> ({agent_id: obs}, {agent_id: info})
    step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)
      where terminateds/truncateds carry per-agent flags plus "__all__".

    Subclasses define `observation_space(agent_id)` / `action_space
    (agent_id)` (gym spaces) so workers can size per-policy networks.
    """

    possible_agents: Tuple[str, ...] = ()

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def observation_space(self, agent_id: str):
        raise NotImplementedError

    def action_space(self, agent_id: str):
        raise NotImplementedError


def make_multi_agent(env_creator):
    """Wrap a single-agent env creator into an N-agent env of independent
    copies (reference: rllib/env/multi_agent_env.py make_multi_agent) —
    agent i steps its own copy; episodes end when all copies end."""

    class _IndependentCopies(MultiAgentEnv):
        def __init__(self, config=None):
            config = dict(config or {})
            self.num = int(config.pop("num_agents", 2))
            self.envs = [env_creator(config) for _ in range(self.num)]
            self.possible_agents = tuple(
                f"agent_{i}" for i in range(self.num))
            self._done = [False] * self.num

        def observation_space(self, agent_id):
            return self.envs[0].observation_space

        def action_space(self, agent_id):
            return self.envs[0].action_space

        def reset(self, *, seed=None):
            obs, infos = {}, {}
            for i, env in enumerate(self.envs):
                o, info = env.reset(
                    seed=None if seed is None else seed + i)
                obs[f"agent_{i}"] = o
                infos[f"agent_{i}"] = info
                self._done[i] = False
            return obs, infos

        def step(self, action_dict):
            obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
            for i, env in enumerate(self.envs):
                aid = f"agent_{i}"
                if self._done[i] or aid not in action_dict:
                    continue
                o, r, te, tr, info = env.step(action_dict[aid])
                obs[aid], rews[aid] = o, r
                terms[aid], truncs[aid], infos[aid] = te, tr, info
                if te or tr:
                    self._done[i] = True
                    obs.pop(aid)  # agent is gone until the next reset
            terms["__all__"] = all(self._done)
            truncs["__all__"] = False
            return obs, rews, terms, truncs, infos

    return _IndependentCopies
