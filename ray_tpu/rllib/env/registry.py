"""Named env registry (reference: ray/tune/registry.py register_env —
tuned_examples name custom envs by string; the worker-side creator
resolves the name without shipping the class through the config)."""

from __future__ import annotations

from typing import Callable, Dict, Optional

_ENVS: Dict[str, Callable] = {}


def register_env(name: str, creator: Callable) -> None:
    """creator(env_config) -> env instance."""
    _ENVS[name] = creator


def resolve_env_creator(env) -> Callable:
    """Uniform env spec resolution: a string resolves through the
    registry (else gym.make); a class/callable is the creator itself.
    Returns creator(env_config) -> env instance."""
    if isinstance(env, str):
        creator = get_registered_env(env)
        if creator is not None:
            return creator
        import gymnasium as gym
        return lambda cfg: gym.make(env, **(cfg or {}))
    return env


def get_registered_env(name: str) -> Optional[Callable]:
    if name not in _ENVS and "." not in name:
        # Lazy-load the in-tree example envs so tuned_examples resolve
        # without an explicit import at the call site.
        try:
            import ray_tpu.rllib.examples.env  # noqa: F401
        except ImportError:
            pass
    return _ENVS.get(name)
