"""PolicyClient: drive episodes against a PolicyServerInput over HTTP.

Reference: rllib/env/policy_client.py:46 — the external simulator's side
of the serving protocol: start_episode / get_action / log_returns /
end_episode.  Stdlib urllib only, so any external process with this one
file's worth of protocol can participate.
"""

from __future__ import annotations

import pickle
import urllib.request
from typing import Optional


class PolicyClient:
    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _call(self, verb: str, body: dict):
        req = urllib.request.Request(
            f"{self.address}/{verb}", data=pickle.dumps(body),
            headers={"Content-Type": "application/octet-stream"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                reply = pickle.loads(r.read())
        except urllib.error.HTTPError as e:
            reply = pickle.loads(e.read())
        if not reply.get("ok"):
            raise RuntimeError(f"policy server error: "
                               f"{reply.get('error')}")
        return reply.get("result")

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        return self._call("start_episode", {"episode_id": episode_id})

    def get_action(self, episode_id: str, observation):
        return self._call("get_action", {"episode_id": episode_id,
                                         "observation": observation})

    def log_action(self, episode_id: str, observation, action):
        self._call("log_action", {"episode_id": episode_id,
                                  "observation": observation,
                                  "action": action})

    def log_returns(self, episode_id: str, reward: float):
        self._call("log_returns", {"episode_id": episode_id,
                                   "reward": float(reward)})

    def end_episode(self, episode_id: str, observation):
        self._call("end_episode", {"episode_id": episode_id,
                                   "observation": observation})
