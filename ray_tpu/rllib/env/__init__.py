from ray_tpu.rllib.env.policy_client import PolicyClient  # noqa: F401
from ray_tpu.rllib.env.policy_server_input import (  # noqa: F401
    PolicyServerInput,
)
