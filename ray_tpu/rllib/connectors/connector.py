"""Connectors: composable transform pipelines between env and policy.

Reference: rllib/connectors/ — per-policy pipelines that adapt raw env
observations into policy inputs (agent connectors) and policy outputs
into env actions (action connectors), carried with checkpoints so serving
uses the exact training-time preprocessing.

Two pipelines per worker:
  obs pipeline:    env obs  -> policy input  (flatten, dtype, filters)
  action pipeline: policy action -> env action (clip/unsquash)

Stateful connectors (MeanStdObsFilter) expose get_state/set_state so
weight sync can carry filter statistics to every worker, the same way
the reference syncs its filters alongside weights.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    def __call__(self, x):
        raise NotImplementedError

    def get_state(self) -> Optional[Dict]:
        return None

    def set_state(self, state: Dict):
        pass


class ObsConnector(Connector):
    """Marker base for observation-side connectors."""


class ActionConnector(Connector):
    """Marker base for action-side connectors."""


class FlattenObsConnector(ObsConnector):
    """Flatten any obs shape to a float32 vector (reference:
    connectors/agent/obs_preproc.py over the flatten preprocessor)."""

    def __call__(self, obs):
        return np.asarray(obs, np.float32).reshape(-1)


class MeanStdObsFilter(ObsConnector):
    """Running mean/std normalization (reference: the MeanStdFilter agent
    connector).  Welford accumulation, per worker.  State travels in the
    worker's weights dict (checkpoint/restore); a receiving worker adopts
    it only when it has seen MORE data than its own (monotonic guard), so
    weight broadcasts never reset a sampler's running estimator."""

    def __init__(self, eps: float = 1e-8):
        self.count = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None
        self.eps = eps

    def __call__(self, obs):
        x = np.asarray(obs, np.float64).reshape(-1)
        if self.mean is None:
            self.mean = np.zeros_like(x)
            self.m2 = np.zeros_like(x)
        self.count += 1
        delta = x - self.mean
        self.mean += delta / self.count
        self.m2 += delta * (x - self.mean)
        if self.count < 2:
            return x.astype(np.float32)
        std = np.sqrt(self.m2 / (self.count - 1)) + self.eps
        return ((x - self.mean) / std).astype(np.float32)

    def get_state(self):
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class ClipActionsConnector(ActionConnector):
    """Clip continuous actions into the env's bounds (reference:
    connectors/action/clip.py)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, action):
        return np.clip(action, self.low, self.high)


class ConnectorPipeline(Connector):
    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, x):
        for c in self.connectors:
            x = c(x)
        return x

    def get_state(self):
        return [c.get_state() for c in self.connectors]

    def set_state(self, states):
        for c, s in zip(self.connectors, states):
            if s is not None:
                c.set_state(s)


def get_default_pipelines(config: Dict, action_space=None):
    """Build the (obs, action) pipelines from config keys
    `obs_filter` ("flatten" | "meanstd") and `clip_actions`."""
    obs: List[Connector] = [FlattenObsConnector()]
    if config.get("obs_filter") == "meanstd":
        obs.append(MeanStdObsFilter())
    act: List[Connector] = []
    if config.get("clip_actions") and action_space is not None \
            and hasattr(action_space, "low"):
        act.append(ClipActionsConnector(action_space.low,
                                        action_space.high))
    return ConnectorPipeline(obs), ConnectorPipeline(act)
