from ray_tpu.rllib.connectors.connector import (
    ActionConnector,
    ClipActionsConnector,
    Connector,
    ConnectorPipeline,
    FlattenObsConnector,
    MeanStdObsFilter,
    ObsConnector,
    get_default_pipelines,
)

__all__ = [
    "Connector", "ConnectorPipeline", "ObsConnector", "ActionConnector",
    "FlattenObsConnector", "MeanStdObsFilter", "ClipActionsConnector",
    "get_default_pipelines",
]
