"""Pixel-observation control envs for world-model algorithms.

Reference: the reference Dreamer is image-based — ConvEncoder/
ConvDecoder over 64x64 frames (rllib/algorithms/dreamer/
dreamer_model.py:23,71) on visual control suites.  PixelPendulum is
that domain class scoped to CI hardware: the classic pendulum swing-up
observed ONLY through a small grayscale frame, so angular velocity is
unobservable from a single observation and the recurrent world model
must integrate it across frames — the property that makes pixel
control a genuinely different problem from proprioception.
"""

from __future__ import annotations

import numpy as np


class PixelPendulum:
    """Pendulum-v1 where the observation is a size x size x 1 grayscale
    rendering of the rod (no cos/sin/velocity vector).  Rewards,
    actions, and dynamics are the underlying env's."""

    def __init__(self, config=None):
        config = config or {}
        import gymnasium as gym
        self.env = gym.make("Pendulum-v1")
        self.size = int(config.get("size", 24))
        self.observation_space = gym.spaces.Box(
            0.0, 1.0, (self.size, self.size, 1), np.float32)
        self.action_space = self.env.action_space
        # Precompute rod sample offsets once; rendering is then a
        # handful of integer scatters per frame.
        self._radii = np.linspace(0.15, 0.95, 3 * self.size)

    def _frame(self) -> np.ndarray:
        theta = float(self.env.unwrapped.state[0])
        img = np.zeros((self.size, self.size), np.float32)
        c = (self.size - 1) / 2.0
        reach = c - 0.5
        # theta = 0 is upright; x right, y up in world coords.
        rr = np.clip(np.round(
            c - self._radii * reach * np.cos(theta)), 0,
            self.size - 1).astype(np.int64)
        cc = np.clip(np.round(
            c + self._radii * reach * np.sin(theta)), 0,
            self.size - 1).astype(np.int64)
        img[rr, cc] = 1.0
        # Pivot marker anchors the geometry.
        img[int(c), int(c)] = 0.5
        return img[..., None]

    def reset(self, seed=None, **kwargs):
        _, info = self.env.reset(seed=seed)
        return self._frame(), info

    def step(self, action):
        _, reward, term, trunc, info = self.env.step(action)
        return self._frame(), reward, term, trunc, info

    def close(self):
        self.env.close()
