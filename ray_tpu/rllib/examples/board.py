"""Two-player zero-sum board games for AlphaZero-style self-play.

Reference: rllib/examples/env/ provides the small diagnostic envs the
reference's alpha_zero learning tests run on; the reference AlphaZero
itself (rllib/algorithms/alpha_zero/) is a two-player MCTS self-play
algorithm over envs exposing get_state/set_state.  ConnectFour here is
that domain class: perfect-information, alternating-move, zero-sum,
with a column-drop action space and a connect-K win rule.

The board is kept in *absolute* encoding (+1 = first player, -1 =
second player, 0 = empty); `canonical_obs()` multiplies by the player
to move so a network always sees itself as +1 — the standard AlphaZero
symmetry trick that halves what the net must learn.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ConnectFour:
    """Connect-K on an R x C grid (default: the classic 6 x 7, K=4).

    Not a gymnasium env on purpose: alternating-move games need
    `player_to_move`, `legal_actions`, and clone/restore, which the
    gym API has no vocabulary for.  AlphaZero drives this interface
    directly (mirroring the reference's requirement that alpha_zero
    envs expose get_state/set_state on top of step)."""

    def __init__(self, config=None):
        config = config or {}
        self.rows = int(config.get("rows", 6))
        self.cols = int(config.get("cols", 7))
        self.k = int(config.get("connect", 4))
        self.reset()

    # ------------------------------------------------------------ core
    def reset(self) -> np.ndarray:
        self.board = np.zeros((self.rows, self.cols), np.int8)
        self.to_move = 1  # +1 moves first
        self.winner: Optional[int] = None  # +1 / -1 / 0 (draw) / None
        self.moves = 0
        return self.canonical_obs()

    @property
    def num_actions(self) -> int:
        return self.cols

    @property
    def obs_dim(self) -> int:
        return self.rows * self.cols

    def legal_actions(self) -> List[int]:
        return [c for c in range(self.cols) if self.board[0, c] == 0]

    def canonical_obs(self) -> np.ndarray:
        """Board from the mover's perspective (mover pieces = +1)."""
        return (self.board * self.to_move).astype(
            np.float32).reshape(-1)

    def apply(self, action: int) -> Tuple[bool, int]:
        """Drop a piece for the player to move.  Returns (terminal,
        winner) with winner in {+1, -1, 0} (0 = draw) once terminal."""
        col = int(action)
        if self.board[0, col] != 0 or self.winner is not None:
            raise ValueError(f"illegal move {col}")
        row = int(np.max(np.nonzero(
            np.append(self.board[:, col], 1) == 0)))
        self.board[row, col] = self.to_move
        self.moves += 1
        if self._wins_at(row, col):
            self.winner = self.to_move
        elif self.moves == self.rows * self.cols:
            self.winner = 0
        self.to_move = -self.to_move
        return self.winner is not None, (self.winner or 0)

    def _wins_at(self, row: int, col: int) -> bool:
        me = self.board[row, col]
        for dr, dc in ((0, 1), (1, 0), (1, 1), (1, -1)):
            run = 1
            for sign in (1, -1):
                r, c = row + sign * dr, col + sign * dc
                while (0 <= r < self.rows and 0 <= c < self.cols
                       and self.board[r, c] == me):
                    run += 1
                    r += sign * dr
                    c += sign * dc
            if run >= self.k:
                return True
        return False

    # -------------------------------------------------- clone/restore
    def get_state(self):
        return (self.board.copy(), self.to_move, self.winner, self.moves)

    def set_state(self, state) -> None:
        board, to_move, winner, moves = state
        self.board = board.copy()
        self.to_move = to_move
        self.winner = winner
        self.moves = moves

    # ------------------------------------------------ scripted players
    def winning_moves(self, player: int) -> List[int]:
        """Columns where `player` wins immediately (used by the greedy
        eval opponent and by tests)."""
        out = []
        save = self.get_state()
        for c in self.legal_actions():
            self.to_move = player
            self.winner = None
            try:
                _, w = self.apply(c)
            except ValueError:
                self.set_state(save)
                continue
            if w == player:
                out.append(c)
            self.set_state(save)
        return out

    def greedy_move(self, rng: np.random.RandomState) -> int:
        """1-ply tactical player: take an immediate win, else block the
        opponent's immediate win, else random — the eval bar opponent."""
        me = self.to_move
        wins = self.winning_moves(me)
        if wins:
            return wins[0]
        blocks = self.winning_moves(-me)
        if blocks:
            return blocks[0]
        legal = self.legal_actions()
        return int(legal[rng.randint(len(legal))])

    def random_move(self, rng: np.random.RandomState) -> int:
        legal = self.legal_actions()
        return int(legal[rng.randint(len(legal))])
