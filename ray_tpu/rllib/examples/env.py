"""In-tree example envs used by tuned_examples and tests (reference:
rllib/examples/env/two_step_game.py, rllib/env/bandit_envs_discrete.py
SimpleContextualBandit, and the small diagnostic envs the reference's
tuned examples lean on).  Importing this module registers each env
under its class name so tuned-example JSON can say "env":
"TwoStepCoopGame" etc."""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv
from ray_tpu.rllib.env.registry import register_env


class TwoStepCoopGame(MultiAgentEnv):
    """The QMIX paper's two-step cooperative matrix game: agent_0's
    first action picks the payoff matrix; in state 2A every joint
    action pays 7, in state 2B the joint payoffs are [[0,1],[1,8]].
    Optimal play (pick B, then both choose action 1) pays 8; greedy
    independent learners settle for 7."""

    possible_agents = ("agent_0", "agent_1")
    _B = np.array([[0.0, 1.0], [1.0, 8.0]])

    def __init__(self, config=None):
        self.stage = 0  # 0 -> choosing, 1 -> matrix A, 2 -> matrix B

    def observation_space(self, agent_id):
        import gymnasium as gym
        return gym.spaces.Box(0.0, 1.0, (3,), np.float32)

    def action_space(self, agent_id):
        import gymnasium as gym
        return gym.spaces.Discrete(2)

    def _obs(self):
        o = np.zeros(3, np.float32)
        o[self.stage] = 1.0
        return {a: o.copy() for a in self.possible_agents}

    def state(self):
        s = np.zeros(3, np.float32)
        s[self.stage] = 1.0
        return s

    def reset(self, *, seed=None):
        self.stage = 0
        return self._obs(), {a: {} for a in self.possible_agents}

    def step(self, action_dict):
        if self.stage == 0:
            self.stage = 1 if action_dict["agent_0"] == 0 else 2
            rews = {a: 0.0 for a in self.possible_agents}
            dones = {"__all__": False}
            return self._obs(), rews, dones, {"__all__": False}, {}
        if self.stage == 1:
            r = 7.0
        else:
            r = float(self._B[action_dict["agent_0"],
                              action_dict["agent_1"]])
        rews = {a: r / 2.0 for a in self.possible_agents}
        return ({}, rews, {"__all__": True}, {"__all__": False}, {})


class CoopTargetSumEnv(MultiAgentEnv):
    """Two agents each emit a scalar in [-1, 1]; the shared reward is
    -(a_0 + a_1 - target)^2 with the target visible to both.  Solving
    it requires coordinating the SPLIT of the target — the centralized
    critic's job."""

    possible_agents = ("agent_0", "agent_1")

    def __init__(self, config=None):
        self._rng = np.random.RandomState(0)
        self.horizon = 5

    def observation_space(self, agent_id):
        import gymnasium as gym
        return gym.spaces.Box(-1.5, 1.5, (1,), np.float32)

    def action_space(self, agent_id):
        import gymnasium as gym
        return gym.spaces.Box(-1.0, 1.0, (1,), np.float32)

    def _obs(self):
        o = np.asarray([self.target], np.float32)
        return {a: o.copy() for a in self.possible_agents}

    def state(self):
        return np.asarray([self.target], np.float32)

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self.target = float(self._rng.uniform(-1.2, 1.2))
        self.t = 0
        return self._obs(), {a: {} for a in self.possible_agents}

    def step(self, action_dict):
        s = float(np.sum([np.asarray(a).reshape(-1)[0]
                          for a in action_dict.values()]))
        r = -(s - self.target) ** 2
        self.t += 1
        done = self.t >= self.horizon
        self.target = float(self._rng.uniform(-1.2, 1.2))
        rews = {a: r / 2.0 for a in self.possible_agents}
        return (self._obs() if not done else {}, rews,
                {"__all__": done}, {"__all__": False}, {})


class SimpleContextualBandit:
    """2-context, 3-arm bandit (reference:
    rllib/env/bandit_envs_discrete.py SimpleContextualBandit): best arm
    depends on the context; regret-free play earns 10 per pull."""

    def __init__(self, config=None):
        import gymnasium as gym
        self.observation_space = gym.spaces.Box(-1.0, 1.0, (2,),
                                                np.float32)
        self.action_space = gym.spaces.Discrete(3)
        self._rng = np.random.RandomState((config or {}).get("seed", 0))
        self.ctx = None

    def reset(self, **kwargs):
        self.ctx = (np.array([-1.0, 1.0], np.float32)
                    if self._rng.rand() < 0.5
                    else np.array([1.0, -1.0], np.float32))
        return self.ctx, {}

    def step(self, action):
        rewards_per_arm = ({0: 10.0, 1: 0.0, 2: 5.0}
                           if self.ctx[0] < 0
                           else {0: 0.0, 1: 10.0, 2: 5.0})
        r = rewards_per_arm[int(action)]
        return self.ctx, r, True, False, {}


class ReachEnv:
    """1-D deterministic reach task: drive x to the origin.  Dense
    quadratic reward makes it solvable in a few hundred updates — a
    fast, non-flaky 'does the DPG machinery learn at all' probe."""

    def __init__(self, config=None):
        import gymnasium as gym
        config = config or {}
        self.observation_space = gym.spaces.Box(-2.0, 2.0, (1,),
                                                np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (1,), np.float32)
        self._rng = np.random.RandomState(config.get("seed", 0))
        self.horizon = config.get("horizon", 40)

    def reset(self, **kwargs):
        self.x = self._rng.uniform(-1.0, 1.0)
        self.t = 0
        return np.array([self.x], np.float32), {}

    def step(self, action):
        self.x = float(np.clip(self.x + 0.2 * float(action[0]),
                               -2.0, 2.0))
        self.t += 1
        reward = -self.x ** 2
        truncated = self.t >= self.horizon
        return (np.array([self.x], np.float32), reward, False,
                truncated, {})


# One call convention everywhere: every example env takes the
# env_config dict positionally (like MultiAgentEnv), so the registered
# creator and the direct-class path (resolve_env_creator returns the
# class, called with env_config) construct identically.
for _cls in (TwoStepCoopGame, CoopTargetSumEnv, SimpleContextualBandit,
             ReachEnv):
    register_env(_cls.__name__, (lambda cls: lambda cfg: cls(cfg))(_cls))
