"""MADDPG: multi-agent DDPG with centralized critics and decentralized
actors (Lowe et al. 2017).

Reference: rllib/algorithms/maddpg/maddpg.py — each agent i trains a
critic Q_i(s, a_1..a_n) that sees every agent's action (stationarizing
the otherwise non-stationary multi-agent learning problem) while its
deterministic actor only sees its own observation, so execution stays
decentralized.  Re-derived jax-first: all agents' critic + actor +
polyak updates compile into one jitted step over stacked per-agent
parameters (vmap over the agent axis replaces the reference's per-agent
tf graphs).

Works on any `MultiAgentEnv` with a fixed team and Box per-agent action
spaces; the centralized state is `env.state()` when defined, else
concatenated observations.
"""

from __future__ import annotations

from typing import Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class _Actor(nn.Module):
    act_dim: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs):
        h = obs
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return jnp.tanh(nn.Dense(self.act_dim)(h))


class _CentralCritic(nn.Module):
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, state, joint_act):
        h = jnp.concatenate([state, joint_act], axis=-1)
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return nn.Dense(1)(h)[..., 0]


class MADDPGConfig:
    def __init__(self):
        self.algo_class = MADDPG
        self._config: Dict = {
            "env": None,
            "env_config": {},
            "actor_lr": 1e-3,
            "critic_lr": 1e-3,
            "gamma": 0.95,
            "tau": 0.99,                # polyak coefficient
            "buffer_capacity": 50_000,
            "train_batch_size": 128,
            "num_sgd_steps": 40,
            "steps_per_iter": 400,
            "learning_starts": 500,
            "exploration_noise": 0.3,
            "noise_anneal_iters": 15,
            "final_noise": 0.05,
            "fcnet_hiddens": (64, 64),
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "MADDPGConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "MADDPGConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "MADDPGConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "MADDPG":
        return MADDPG(config=self.to_dict())


class MADDPG(Trainable):
    def setup(self, config: Dict):
        defaults = MADDPGConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        from ray_tpu.rllib.env.registry import resolve_env_creator
        self.env = resolve_env_creator(self.cfg["env"])(
            self.cfg["env_config"])
        self.agents = list(self.env.possible_agents)
        self.n = len(self.agents)
        space0 = self.env.action_space(self.agents[0])
        self.act_dim = int(np.prod(space0.shape))
        self._act_low = np.asarray(space0.low, np.float32)
        self._act_high = np.asarray(space0.high, np.float32)
        self._scale = (self._act_high - self._act_low) / 2.0
        self._center = (self._act_high + self._act_low) / 2.0
        self.obs_dim = int(np.prod(
            self.env.observation_space(self.agents[0]).shape))
        self._obs, _ = self.env.reset(seed=self.cfg["seed"])
        self.state_dim = (int(np.prod(np.shape(self.env.state())))
                          if hasattr(self.env, "state")
                          else self.obs_dim * self.n)
        hiddens = tuple(self.cfg["fcnet_hiddens"])
        self.actor = _Actor(act_dim=self.act_dim, hiddens=hiddens)
        self.critic = _CentralCritic(hiddens=hiddens)
        rng = jax.random.PRNGKey(self.cfg["seed"])
        keys = jax.random.split(rng, 2 * self.n)
        zo = jnp.zeros((1, self.obs_dim), jnp.float32)
        zs = jnp.zeros((1, self.state_dim), jnp.float32)
        zja = jnp.zeros((1, self.act_dim * self.n), jnp.float32)
        # Per-agent parameters stacked on a leading agent axis (vmap'd
        # in the train step).
        self.actor_params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[self.actor.init(keys[i], zo) for i in range(self.n)])
        self.critic_params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[self.critic.init(keys[self.n + i], zs, zja)
              for i in range(self.n)])
        self.target_actor_params = self.actor_params
        self.target_critic_params = self.critic_params
        self.actor_tx = optax.adam(self.cfg["actor_lr"])
        self.critic_tx = optax.adam(self.cfg["critic_lr"])
        self.actor_opt = self.actor_tx.init(self.actor_params)
        self.critic_opt = self.critic_tx.init(self.critic_params)
        self._act_forward = jax.jit(
            jax.vmap(self.actor.apply, in_axes=(0, 0)))
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._buffer: List[Dict] = []
        self._iter = 0
        self._timesteps_total = 0
        self._episode_rewards: List[float] = []
        self._ep_reward = 0.0

    # ---------------------------------------------------------- plumbing
    def _state(self, obs: Dict) -> np.ndarray:
        if hasattr(self.env, "state"):
            return np.asarray(self.env.state(), np.float32).reshape(-1)
        return np.concatenate([np.asarray(obs[a], np.float32).reshape(-1)
                               for a in self.agents])

    def _stack_obs(self, obs: Dict) -> np.ndarray:
        return np.stack([np.asarray(obs[a], np.float32).reshape(-1)
                         for a in self.agents])

    def _actions(self, obs: Dict, noise: float) -> Dict:
        stacked = jnp.asarray(self._stack_obs(obs))[:, None, :]
        raw = np.asarray(self._act_forward(self.actor_params,
                                           stacked))[:, 0, :]
        raw = raw + noise * self._rng.randn(*raw.shape)
        raw = np.clip(raw, -1.0, 1.0).astype(np.float32)
        acts = {}
        for i, a in enumerate(self.agents):
            shape = self.env.action_space(a).shape
            acts[a] = (raw[i] * self._scale
                       + self._center).astype(np.float32).reshape(shape)
        return acts, raw

    def _noise(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._iter / max(cfg["noise_anneal_iters"], 1))
        return (cfg["exploration_noise"]
                + frac * (cfg["final_noise"]
                          - cfg["exploration_noise"]))

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, actor_params, critic_params, ta_params,
                         tc_params, actor_opt, critic_opt, batch):
        cfg = self.cfg
        gamma, tau = cfg["gamma"], cfg["tau"]
        B = batch["state"].shape[0]
        n, A = self.n, self.act_dim

        # Next joint action from TARGET actors.
        next_acts = jax.vmap(self.actor.apply, in_axes=(0, 1),
                             out_axes=1)(ta_params, batch["next_obs"])
        next_joint = next_acts.reshape(B, n * A)

        def critic_loss_fn(cp):
            tq = jax.vmap(self.critic.apply,
                          in_axes=(0, None, None), out_axes=1)(
                tc_params, batch["next_state"], next_joint)
            target = batch["rewards"] + gamma * tq * (
                1.0 - batch["done"][:, None].astype(jnp.float32))
            q = jax.vmap(self.critic.apply,
                         in_axes=(0, None, None), out_axes=1)(
                cp, batch["state"],
                batch["actions"].reshape(B, n * A))
            return ((q - jax.lax.stop_gradient(target)) ** 2).mean()

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            critic_params)
        c_updates, critic_opt = self.critic_tx.update(
            c_grads, critic_opt, critic_params)
        critic_params = optax.apply_updates(critic_params, c_updates)

        def actor_loss_fn(ap):
            # Each agent's actor acts on its own obs; others' actions
            # come from the batch (MADDPG's decentralized-actor grad).
            cur = jax.vmap(self.actor.apply, in_axes=(0, 1),
                           out_axes=1)(ap, batch["obs"])
            total = 0.0
            for i in range(n):
                joint = batch["actions"].at[:, i, :].set(cur[:, i, :])
                q_i = self.critic.apply(
                    jax.tree_util.tree_map(lambda x: x[i],
                                           critic_params),
                    batch["state"], joint.reshape(B, n * A))
                total = total - q_i.mean()
            return total / n

        a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(actor_params)
        a_updates, actor_opt = self.actor_tx.update(a_grads, actor_opt,
                                                    actor_params)
        actor_params = optax.apply_updates(actor_params, a_updates)

        ta_params = jax.tree_util.tree_map(
            lambda t, p: tau * t + (1 - tau) * p, ta_params, actor_params)
        tc_params = jax.tree_util.tree_map(
            lambda t, p: tau * t + (1 - tau) * p, tc_params,
            critic_params)
        return (actor_params, critic_params, ta_params, tc_params,
                actor_opt, critic_opt,
                {"critic_loss": c_loss, "actor_loss": a_loss})

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        noise = self._noise()
        for _ in range(cfg["steps_per_iter"]):
            actions, raw = self._actions(self._obs, noise)
            obs2, rews, terms, truncs, _ = self.env.step(actions)
            done = terms.get("__all__", False) or truncs.get(
                "__all__", False)
            self._buffer.append({
                "obs": self._stack_obs(self._obs),
                "state": self._state(self._obs),
                "actions": raw.astype(np.float32),
                "rewards": np.asarray(
                    [rews[a] for a in self.agents], np.float32),
                "done": done,
                "next_obs": (self._stack_obs(obs2) if obs2
                             else self._stack_obs(self._obs)),
                "next_state": (self._state(obs2) if obs2
                               else self._state(self._obs))})
            if len(self._buffer) > cfg["buffer_capacity"]:
                self._buffer.pop(0)
            self._ep_reward += float(sum(rews.values()))
            self._timesteps_total += 1
            if done:
                self._episode_rewards.append(self._ep_reward)
                self._ep_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = obs2
        stats: Dict = {}
        if len(self._buffer) >= cfg["learning_starts"]:
            for _ in range(cfg["num_sgd_steps"]):
                idx = self._rng.randint(0, len(self._buffer),
                                        cfg["train_batch_size"])
                cols = {k: jnp.asarray(np.stack(
                    [self._buffer[i][k] for i in idx]))
                    for k in ("obs", "state", "actions", "rewards",
                              "done", "next_obs", "next_state")}
                (self.actor_params, self.critic_params,
                 self.target_actor_params, self.target_critic_params,
                 self.actor_opt, self.critic_opt, jstats) = \
                    self._train_step(
                        self.actor_params, self.critic_params,
                        self.target_actor_params,
                        self.target_critic_params,
                        self.actor_opt, self.critic_opt, cols)
            stats = {k: float(v) for k, v in jstats.items()}
        recent = self._episode_rewards[-50:]
        return {"episode_reward_mean": (float(np.mean(recent))
                                        if recent else np.nan),
                "info": {"learner": stats},
                "exploration_noise": noise,
                "timesteps_total": self._timesteps_total}

    def greedy_actions(self, obs: Dict) -> Dict:
        actions, _ = self._actions(obs, noise=0.0)
        return actions

    def save_checkpoint(self) -> Dict:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa
        return {"actor": to_np(self.actor_params),
                "critic": to_np(self.critic_params),
                "iter": self._iter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            to_j = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa
            self.actor_params = to_j(data["actor"])
            self.critic_params = to_j(data["critic"])
            self.target_actor_params = self.actor_params
            self.target_critic_params = self.critic_params
            self._iter = data.get("iter", 0)
            self._timesteps_total = data.get("timesteps_total", 0)
