from ray_tpu.rllib.algorithms.maddpg.maddpg import (  # noqa: F401
    MADDPG,
    MADDPGConfig,
)
