from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig  # noqa: F401
