"""SAC: off-policy soft actor-critic with replay.

Reference: rllib/algorithms/sac/sac.py (training_step: store rollouts in
the replay buffer, SGD on replay batches, polyak target updates).
Discrete envs use the categorical soft-Q policy; Box envs the
tanh-Gaussian reparameterized one — either way the stochastic policy
itself explores, so no epsilon schedule is needed.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_sac_policy import SACPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(SAC)
        self._config.update({
            "lr": 3e-4,
            "tau": 0.995,              # polyak coefficient per update
            "initial_alpha": 0.1,
            "buffer_capacity": 50_000,
            "learning_starts": 500,
            "train_batch_size": 500,   # env steps collected per iter
            "sgd_batch_size": 128,
            "num_sgd_steps": 64,
        })


class SAC(Algorithm):
    policy_cls = SACPolicy

    def _extra_defaults(self) -> Dict:
        return dict(SACConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        self.buffer = ReplayBuffer(self.algo_config["buffer_capacity"],
                                   seed=self.algo_config["seed"])

    def training_step(self) -> Dict:
        cfg = self.algo_config
        per_worker = max(1, cfg["train_batch_size"]
                         // max(1, len(self.workers.remote_workers)))
        if self.workers.remote_workers:
            batches = ray_tpu.get(
                self.workers.sample_all(per_worker), timeout=600)
        else:
            batches = [self.workers.local_worker.sample(per_worker)]
        batch = SampleBatch.concat_samples(batches)
        self.buffer.add(batch)
        self._timesteps_total += batch.count

        policy = self.workers.local_worker.policy
        stats: Dict = {}
        if len(self.buffer) >= cfg["learning_starts"]:
            for _ in range(cfg["num_sgd_steps"]):
                stats = policy.learn_on_batch(
                    self.buffer.sample(cfg["sgd_batch_size"]))
                policy.update_target()
        if self.workers.remote_workers:
            self.workers.sync_weights()
        return {"info": {"learner": stats,
                         "buffer_size": len(self.buffer)},
                "num_env_steps_trained": batch.count}
