from ray_tpu.rllib.algorithms.appo.appo import (  # noqa: F401
    APPO,
    APPOConfig,
)
