"""APPO: asynchronous PPO — IMPALA's async architecture with PPO's
clipped surrogate objective.

Reference: rllib/algorithms/appo/appo.py (subclasses Impala, swaps the
loss for the clipped surrogate + periodic target update; we scope to the
clipped-surrogate form over slightly-stale rollouts).  The architecture
(rollout workers streaming into a learner thread, weights broadcast on a
cadence) is inherited from our Impala.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.rllib.algorithms.algorithm import AlgorithmConfig
from ray_tpu.rllib.algorithms.impala.impala import Impala


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(APPO)
        self._config.update({
            "loss": "ppo",          # clipped surrogate on async rollouts
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "broadcast_interval": 1,
            "min_steps_per_iteration": 1000,
        })


class APPO(Impala):
    def _extra_defaults(self) -> Dict:
        return {"loss": "ppo", "clip_param": 0.2, "vf_loss_coeff": 0.5,
                "entropy_coeff": 0.01, "broadcast_interval": 1,
                "min_steps_per_iteration": 1000}
