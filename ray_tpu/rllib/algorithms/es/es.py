"""ES: evolution strategies (OpenAI-ES) — derivative-free policy search
by sampling parameter perturbations and estimating the gradient from
episode returns.

Reference: rllib/algorithms/es/es.py (Worker actors evaluate mirrored
noise pairs; the driver aggregates rank-normalized returns into a
gradient step; shared noise table).  Re-designed for this runtime:
evaluations are stateless remote *tasks* fanned out per iteration (the
framework's cheap-task path replaces the reference's persistent noise
workers), and the policy is a tiny numpy MLP — rollouts are pure CPU
env-stepping where jax tracing would be overhead, so the hot loop stays
numpy while the framework supplies the parallelism.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.tune.trainable import Trainable


def _mlp_shapes(obs_dim: int, num_actions: int,
                hiddens: Tuple[int, ...]) -> List[Tuple[int, int]]:
    dims = (obs_dim,) + tuple(hiddens) + (num_actions,)
    return [(dims[i], dims[i + 1]) for i in range(len(dims) - 1)]


def _unflatten(flat: np.ndarray, shapes) -> List[Tuple[np.ndarray,
                                                       np.ndarray]]:
    layers, off = [], 0
    for n_in, n_out in shapes:
        w = flat[off:off + n_in * n_out].reshape(n_in, n_out)
        off += n_in * n_out
        b = flat[off:off + n_out]
        off += n_out
        layers.append((w, b))
    return layers


def _mlp_act(layers, obs: np.ndarray) -> int:
    h = obs
    for i, (w, b) in enumerate(layers):
        h = h @ w + b
        if i < len(layers) - 1:
            h = np.tanh(h)
    return int(np.argmax(h))


def _episode_return(layers, env, max_steps: int,
                    seed: int) -> Tuple[float, int]:
    obs, _ = env.reset(seed=seed)
    total = 0.0
    steps = 0
    for _ in range(max_steps):
        obs, reward, terminated, truncated, _ = env.step(
            _mlp_act(layers, obs))
        total += float(reward)
        steps += 1
        if terminated or truncated:
            break
    return total, steps


def _es_eval(flat_params: np.ndarray, noise_seed: int, sigma: float,
             env_name: str, env_config: Dict, shapes,
             episodes: int, max_steps: int) -> Tuple[int, float, float,
                                                     int]:
    """Evaluate one mirrored perturbation pair (+eps, -eps).

    Runs as a remote task; the same noise is regenerated from the seed on
    the driver (the reference's shared-noise-table trick without the
    table: the seed IS the index)."""
    import gymnasium as gym
    rng = np.random.RandomState(noise_seed)
    eps = rng.randn(flat_params.size).astype(np.float32)
    env = gym.make(env_name, **(env_config or {}))
    steps = 0
    rets = []
    for sign in (1.0, -1.0):
        layers = _unflatten(flat_params + sign * sigma * eps, shapes)
        r = 0.0
        for ep in range(episodes):
            ret, n = _episode_return(layers, env, max_steps,
                                     seed=noise_seed * 1000 + ep)
            r += ret
            steps += n
        rets.append(r / episodes)
    env.close()
    return noise_seed, rets[0], rets[1], steps


class ESConfig:
    def __init__(self):
        self.algo_class = ES
        self._config: Dict = {
            "env": "CartPole-v1",
            "env_config": {},
            "pop_size": 16,          # mirrored pairs per iteration
            "sigma": 0.05,
            "lr": 0.03,
            "episodes_per_eval": 1,
            "max_episode_steps": 500,
            "fcnet_hiddens": (32, 32),
            "seed": 0,
            "l2_coeff": 0.005,
        }

    def environment(self, env=None, env_config=None) -> "ESConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "ESConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "ESConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "ES":
        return ES(config=self.to_dict())


class ES(Trainable):
    """Each train() = one ES generation: fan out pop_size mirrored
    evaluations as tasks, rank-normalize returns, take one gradient
    step (reference es.py _train)."""

    def setup(self, config: Dict):
        defaults = ESConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        import gymnasium as gym
        env = gym.make(self.cfg["env"], **self.cfg["env_config"])
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()
        self.shapes = _mlp_shapes(obs_dim, num_actions,
                                  tuple(self.cfg["fcnet_hiddens"]))
        n = sum(i * o + o for i, o in self.shapes)
        rng = np.random.RandomState(self.cfg["seed"])
        self.flat_params = (rng.randn(n) * 0.1).astype(np.float32)
        self._eval_task = ray_tpu.remote(_es_eval)
        self._next_seed = self.cfg["seed"] * 100_000 + 1
        self._timesteps_total = 0

    def step(self) -> Dict:
        cfg = self.cfg
        seeds = [self._next_seed + i for i in range(cfg["pop_size"])]
        self._next_seed += cfg["pop_size"]
        params_ref = ray_tpu.put(self.flat_params)
        refs = [self._eval_task.remote(
            params_ref, s, cfg["sigma"], cfg["env"], cfg["env_config"],
            self.shapes, cfg["episodes_per_eval"],
            cfg["max_episode_steps"]) for s in seeds]
        results = ray_tpu.get(refs, timeout=600)

        # Rank normalization over all 2*pop returns (es.py
        # compute_centered_ranks).
        rets = np.array([[rp, rn] for _, rp, rn, _ in results],
                        np.float32)
        flat_rets = rets.reshape(-1)
        ranks = np.empty_like(flat_rets)
        ranks[flat_rets.argsort()] = np.arange(flat_rets.size)
        centered = (ranks / (flat_rets.size - 1) - 0.5).reshape(
            rets.shape)

        grad = np.zeros_like(self.flat_params)
        for (seed, _, _, steps), (cp, cn) in zip(results, centered):
            rng = np.random.RandomState(seed)
            eps = rng.randn(self.flat_params.size).astype(np.float32)
            grad += (cp - cn) * eps
            self._timesteps_total += steps
        grad /= (2 * cfg["pop_size"] * cfg["sigma"])
        self.flat_params = ((1 - cfg["l2_coeff"] * cfg["lr"])
                            * self.flat_params
                            + cfg["lr"] * grad).astype(np.float32)

        # Report the unperturbed policy's return as the learning metric.
        import gymnasium as gym
        env = gym.make(cfg["env"], **cfg["env_config"])
        layers = _unflatten(self.flat_params, self.shapes)
        eval_ret, _ = _episode_return(layers, env,
                                      cfg["max_episode_steps"],
                                      seed=int(self._next_seed))
        env.close()
        return {"episode_reward_mean": eval_ret,
                "pop_reward_mean": float(rets.mean()),
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        return {"flat_params": self.flat_params,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.flat_params = data["flat_params"]
            self._timesteps_total = data.get("timesteps_total", 0)
