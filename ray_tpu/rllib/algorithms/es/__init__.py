from ray_tpu.rllib.algorithms.es.es import ES, ESConfig  # noqa: F401
