"""Ape-X DQN: distributed replay — samplers, replay shards, one learner.

Reference: rllib/algorithms/apex_dqn/apex_dqn.py — N rollout workers with
a per-worker epsilon ladder push experience straight into M REPLAY ACTORS
(sharded buffers); the learner loop pulls training batches from the
shards round-robin while sampling continues, and broadcasts weights
periodically.  Decoupling sampling from learning is the point: neither
waits on the other (throughput-positive vs plain DQN's lockstep loop).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_q_policy import JaxQPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class ReplayActor:
    """One shard of the distributed replay memory (reference:
    apex_dqn's ReplayActor over a PRIORITIZED buffer shard — the
    distributed prioritization is Ape-X's namesake mechanism)."""

    def __init__(self, capacity: int, seed: int, prioritized: bool = True,
                 alpha: float = 0.6, beta: float = 0.4):
        from ray_tpu.rllib.utils.replay_buffers import make_buffer
        self.buffer = make_buffer(
            {"prioritized_replay": prioritized,
             "prioritized_replay_alpha": alpha,
             "prioritized_replay_beta": beta},
            capacity=capacity, seed=seed)
        self.prioritized = prioritized
        self.added = 0

    def add(self, batch: SampleBatch) -> int:
        self.buffer.add(batch)
        self.added += batch.count
        return batch.count

    def ready(self, min_size: int) -> bool:
        return len(self.buffer) >= min_size

    def replay(self, batch_size: int):
        if len(self.buffer) == 0:
            return None
        return self.buffer.sample(batch_size)

    def update_priorities(self, idx, td_errors) -> bool:
        """Learner feedback: fresh TD errors for rows sampled from THIS
        shard (reference: apex learner's priority update round trip)."""
        if self.prioritized:
            self.buffer.update_priorities(idx, td_errors)
        return True

    def stats(self) -> Dict:
        return {"size": len(self.buffer), "added": self.added}


class ApexDQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(ApexDQN)
        self._config.update({
            "lr": 1e-3,
            "num_replay_shards": 2,
            "buffer_capacity": 50_000,
            "learning_starts": 500,
            "train_batch_size": 1000,     # env steps sampled per iter
            "sgd_batch_size": 64,
            "num_sgd_steps": 40,
            "target_update_freq": 2,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.02,
            "epsilon_anneal_iters": 10,
            # Per-worker epsilon ladder (reference: Ape-X's per-actor
            # exploration schedule eps_i = eps^(1 + i/(N-1) * alpha)).
            "epsilon_ladder_alpha": 3.0,
            # Distributed prioritized replay — on by default: Ape-X
            # without prioritization is just parallel DQN.
            "prioritized_replay": True,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
        })


class ApexDQN(Algorithm):
    policy_cls = JaxQPolicy

    def _extra_defaults(self) -> Dict:
        return dict(ApexDQNConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        cfg = self.algo_config
        shards = max(1, cfg["num_replay_shards"])
        replay_cls = ray_tpu.remote(ReplayActor)
        per_shard = max(1, cfg["buffer_capacity"] // shards)
        self.replay_actors = [
            replay_cls.options(num_cpus=0).remote(
                per_shard, cfg["seed"] + i,
                prioritized=cfg.get("prioritized_replay", True),
                alpha=cfg["prioritized_replay_alpha"],
                beta=cfg["prioritized_replay_beta"])
            for i in range(shards)]
        self._iter = 0
        self._replay_rr = 0
        self._sample_refs: List = []
        self._add_refs: List = []

    def _worker_epsilons(self, base: float) -> List[float]:
        """Epsilon ladder: worker i explores at base^(1+alpha*i/(N-1))."""
        cfg = self.algo_config
        n = max(1, len(self.workers.remote_workers))
        alpha = cfg["epsilon_ladder_alpha"]
        out = []
        for i in range(n):
            exp = 1.0 + alpha * (i / max(1, n - 1))
            out.append(float(np.clip(base ** exp, cfg["final_epsilon"],
                                     1.0)))
        return out

    def _base_epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._iter / max(cfg["epsilon_anneal_iters"], 1))
        return (cfg["initial_epsilon"]
                + frac * (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    def training_step(self) -> Dict:
        cfg = self.algo_config
        self._iter += 1
        workers = self.workers.remote_workers
        policy = self.workers.local_worker.policy

        # 1. Kick off ASYNC sampling on every worker (per-worker epsilon
        # ladder: low-index workers exploit, high-index explore).
        # Stragglers carried over from the previous iteration stay in
        # the pool — their experience routes when they finish.
        carried = list(self._sample_refs)
        if workers:
            eps = self._worker_epsilons(self._base_epsilon())
            weights = policy.get_weights()
            per_worker = max(1, cfg["train_batch_size"] // len(workers))
            fresh = []
            for i, w in enumerate(workers):
                wcopy = dict(weights)
                wcopy["epsilon"] = eps[i]
                # Ordered before sample below; its get() observes errors.
                w.set_weights.remote(ray_tpu.put(wcopy))  # noqa: RTL002
                fresh.append(w.sample.remote(per_worker))
        else:
            self.workers.local_worker.policy.epsilon = self._base_epsilon()
            b = self.workers.local_worker.sample(cfg["train_batch_size"])
            fresh = [ray_tpu.put(b)]
        self._sample_refs = carried + fresh

        # 2. Route finished fragments into replay shards WITHOUT waiting
        # for stragglers (async pipeline: learner trains below while the
        # slow workers keep sampling).
        ready, pending = ray_tpu.wait(
            list(self._sample_refs),
            num_returns=len(self._sample_refs), timeout=10)
        added = 0
        for ref in ready:
            shard = self.replay_actors[self._replay_rr
                                       % len(self.replay_actors)]
            self._replay_rr += 1
            self._add_refs.append(shard.add.remote(ref))
            added += 1
        self._sample_refs = list(pending)
        # Reap completed adds (keep the pipeline bounded).
        if self._add_refs:
            done, self._add_refs = ray_tpu.wait(
                self._add_refs, num_returns=len(self._add_refs),
                timeout=30)
            self._timesteps_total += sum(ray_tpu.get(done, timeout=60))

        # 3. Learner: pull batches from shards round-robin and SGD.
        stats: Dict = {}
        trained = 0
        readiness = ray_tpu.get(
            [ra.ready.remote(cfg["learning_starts"]
                             // len(self.replay_actors))
             for ra in self.replay_actors], timeout=60)
        if any(readiness):
            live = [ra for ra, ok in zip(self.replay_actors, readiness)
                    if ok]
            # Prefetch: request the next replay batch while training on
            # the current one (the reference's learner thread overlap).
            prioritized = cfg.get("prioritized_replay", True)
            pending_batch = live[0].replay.remote(cfg["sgd_batch_size"])
            pending_shard = live[0]
            for i in range(cfg["num_sgd_steps"]):
                nxt_shard = live[(i + 1) % len(live)]
                nxt = nxt_shard.replay.remote(cfg["sgd_batch_size"])
                batch = ray_tpu.get(pending_batch, timeout=120)
                shard = pending_shard
                pending_batch, pending_shard = nxt, nxt_shard
                if batch is None:
                    continue
                stats = policy.learn_on_batch(batch)
                if prioritized and "batch_indexes" in batch:
                    # Fire-and-forget priority feedback to the shard the
                    # rows came from; the learner never blocks on it.
                    shard.update_priorities.remote(  # noqa: RTL002
                        batch["batch_indexes"], policy.last_td_errors)
                trained += batch.count
            ray_tpu.get(pending_batch, timeout=120)
            if self._iter % cfg["target_update_freq"] == 0:
                policy.update_target()
        return {"info": {"learner": stats,
                         "replay_shards": len(self.replay_actors)},
                "num_env_steps_trained": trained,
                "fragments_routed": added}

    def cleanup(self):
        for ra in self.replay_actors:
            try:
                ray_tpu.kill(ra)
            except Exception:
                pass
        super().cleanup()