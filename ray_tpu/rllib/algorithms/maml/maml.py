"""MAML: model-agnostic meta-learning for RL (Finn et al. 2017).

Reference: rllib/algorithms/maml/maml.py — meta-train a policy
initialization such that ONE inner-loop policy-gradient step on a new
task's rollouts yields a good task-specific policy; the outer objective
is the post-adaptation return, differentiated THROUGH the inner update.

Re-designed jax-first: where the reference splits inner adaptation
across worker processes and approximates the meta-gradient, here the
whole meta-objective (inner rollout surrogate -> SGD step -> outer
surrogate at the adapted params) is one differentiable jitted function
— `jax.grad` through the inner `jax.grad` gives the EXACT second-order
MAML gradient.  Rollouts are numpy env loops on the host (data
collection), learning is pure jax.

Task distribution: any callable `task_sampler(rng) -> env_config`; the
built-in benchmark is a goal-conditioned 2D point navigator (the
reference's classic MAML sanity task family).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class PointGoalEnv:
    """2D point mass; action = velocity in [-0.1, 0.1]^2; reward =
    -distance to a per-task goal the agent must DISCOVER from reward
    (the goal is not observed — adaptation is the only way to find it).
    """

    def __init__(self, config: Optional[Dict] = None):
        config = dict(config or {})
        self.goal = np.asarray(config.get("goal", (0.5, 0.5)),
                               np.float32)
        self.horizon = int(config.get("horizon", 20))

    def reset(self, *, seed: Optional[int] = None):
        self.pos = np.zeros(2, np.float32)
        self.t = 0
        return self.pos.copy(), {}

    def step(self, action):
        a = np.clip(np.asarray(action, np.float32).reshape(2),
                    -0.1, 0.1)
        self.pos = self.pos + a
        self.t += 1
        reward = -float(np.linalg.norm(self.pos - self.goal))
        done = self.t >= self.horizon
        return self.pos.copy(), reward, False, done, {}


def _default_task_sampler(rng: np.random.RandomState) -> Dict:
    angle = rng.uniform(0, 2 * np.pi)
    return {"goal": (0.5 * np.cos(angle), 0.5 * np.sin(angle))}


class _GaussianPolicy(nn.Module):
    """Mean squashed into the env's action range (PointGoalEnv clips at
    +-0.1 — an unsquashed Gaussian saturates the clip and starves the
    likelihood-ratio gradient); std sized to the range."""

    act_dim: int
    act_scale: float = 0.1
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs):
        h = obs
        for width in self.hiddens:
            h = nn.tanh(nn.Dense(width)(h))
        mean = self.act_scale * jnp.tanh(nn.Dense(self.act_dim)(h))
        log_std = self.param("log_std", nn.initializers.constant(-2.5),
                             (self.act_dim,))
        return mean, jnp.broadcast_to(log_std, mean.shape)


class MAMLConfig:
    def __init__(self):
        self.algo_class = MAML
        self._config: Dict = {
            "env": PointGoalEnv,
            "task_sampler": _default_task_sampler,
            "meta_batch_size": 8,      # tasks per meta-step
            "episodes_per_task": 8,    # rollouts for inner AND outer
            "horizon": 20,
            "env_config": {},
            "act_dim": None,     # probed from env.action_space, else 2
            "inner_lr": 0.1,
            "outer_lr": 1e-3,
            "inner_steps": 1,
            "gamma": 0.99,
            "fcnet_hiddens": (64, 64),
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "MAMLConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "MAMLConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "MAMLConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "MAML":
        return self.algo_class(config=self.to_dict())


class MAML(Trainable):
    def setup(self, config: Dict):
        defaults = MAMLConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        probe = self.cfg["env"](dict(self.cfg.get("env_config") or {}))
        obs0, _ = probe.reset(seed=0)
        self.obs_dim = int(np.prod(np.shape(obs0)))
        space = getattr(probe, "action_space", None)
        self.act_dim = (self.cfg["act_dim"]
                        or (int(np.prod(space.shape))
                            if space is not None else 2))
        self.policy = _GaussianPolicy(
            act_dim=self.act_dim,
            hiddens=tuple(self.cfg["fcnet_hiddens"]))
        rng = jax.random.PRNGKey(self.cfg["seed"])
        self.params = self.policy.init(
            rng, jnp.zeros((1, self.obs_dim), jnp.float32))
        # Clipped outer optimizer: the exact second-order meta-gradient
        # has heavy tails (it differentiates THROUGH a noisy inner PG
        # step); unclipped adam walks the meta-init off a cliff after
        # ~30 meta-iterations (measured on the point benchmark).
        self.tx = optax.chain(optax.clip_by_global_norm(1.0),
                              optax.adam(self.cfg["outer_lr"]))
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._forward = jax.jit(self.policy.apply)
        self._meta_grad = jax.jit(jax.value_and_grad(self._meta_loss))
        self._adapt = jax.jit(self._adapted_params)
        self._iter = 0

    # ---------------------------------------------------------- rollouts
    def _sample_action(self, params, obs: np.ndarray) -> np.ndarray:
        mean, log_std = self._forward(
            params, jnp.asarray(obs, jnp.float32)[None])
        mean = np.asarray(mean)[0]
        std = np.exp(np.asarray(log_std)[0])
        return (mean + std * self._rng.randn(self.act_dim)).astype(
            np.float32)

    def _collect(self, params, env_config: Dict) -> Dict[str, np.ndarray]:
        """Episodes under `params`; returns obs/actions/returns-to-go."""
        cfg = self.cfg
        env = cfg["env"](dict(env_config, horizon=cfg["horizon"]))
        rows = {"obs": [], "actions": [], "rtg": []}
        total = 0.0
        for ep in range(cfg["episodes_per_task"]):
            obs, _ = env.reset(seed=int(self._rng.randint(2**31)))
            ep_obs, ep_act, ep_rew = [], [], []
            for _ in range(cfg["horizon"]):
                a = self._sample_action(params, obs)
                obs2, r, term, trunc, _ = env.step(a)
                ep_obs.append(obs)
                ep_act.append(a)
                ep_rew.append(r)
                total += r
                obs = obs2
                if term or trunc:
                    break
            g = 0.0
            rtg = []
            for r in reversed(ep_rew):
                g = r + cfg["gamma"] * g
                rtg.append(g)
            rtg.reverse()
            rows["obs"] += ep_obs
            rows["actions"] += ep_act
            rows["rtg"] += rtg
        batch = {k: np.asarray(v, np.float32) for k, v in rows.items()}
        # Advantage = normalized centered return (per-task baseline).
        adv = batch["rtg"] - batch["rtg"].mean()
        batch["adv"] = adv / max(adv.std(), 1e-6)
        batch["mean_reward"] = total / cfg["episodes_per_task"]
        return batch

    # ---------------------------------------------------------- learning
    def _pg_surrogate(self, params, batch) -> jnp.ndarray:
        mean, log_std = self.policy.apply(params, batch["obs"])
        var = jnp.exp(2 * log_std)
        logp = (-0.5 * ((batch["actions"] - mean) ** 2 / var
                        + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)
        return -(logp * batch["adv"]).mean()

    def _adapted_params(self, params, inner_batch):
        """One (or more) inner policy-gradient steps — plain SGD, kept
        differentiable so the meta-gradient flows through it."""
        lr = self.cfg["inner_lr"]
        for _ in range(self.cfg["inner_steps"]):
            grads = jax.grad(self._pg_surrogate)(params, inner_batch)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
        return params

    def _meta_loss(self, params, inner_batch, outer_batch):
        adapted = self._adapted_params(params, inner_batch)
        return self._pg_surrogate(adapted, outer_batch)

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        meta_grads = None
        pre_rewards, post_rewards = [], []
        for _ in range(cfg["meta_batch_size"]):
            task = cfg["task_sampler"](self._rng)
            inner = self._collect(self.params, task)
            pre_rewards.append(inner.pop("mean_reward"))
            adapted = self._adapt(
                self.params, {k: jnp.asarray(v)
                              for k, v in inner.items()})
            outer = self._collect(adapted, task)
            post_rewards.append(outer.pop("mean_reward"))
            _, g = self._meta_grad(
                self.params,
                {k: jnp.asarray(v) for k, v in inner.items()},
                {k: jnp.asarray(v) for k, v in outer.items()})
            meta_grads = g if meta_grads is None else \
                jax.tree_util.tree_map(jnp.add, meta_grads, g)
        meta_grads = jax.tree_util.tree_map(
            lambda x: x / cfg["meta_batch_size"], meta_grads)
        updates, self.opt_state = self.tx.update(
            meta_grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        return {
            "episode_reward_mean": float(np.mean(post_rewards)),
            "pre_adaptation_reward": float(np.mean(pre_rewards)),
            "post_adaptation_reward": float(np.mean(post_rewards)),
            "adaptation_gain": float(np.mean(post_rewards)
                                     - np.mean(pre_rewards)),
            "training_iteration_": self._iter,
        }

    def adapt_to(self, env_config: Dict):
        """Task-time API: collect once with the meta-policy, take the
        inner step, return adapted params (what MAML is FOR)."""
        inner = self._collect(self.params, env_config)
        inner.pop("mean_reward")
        return self._adapt(self.params,
                           {k: jnp.asarray(v) for k, v in inner.items()})

    def evaluate(self, params, env_config: Dict,
                 deterministic: bool = True) -> float:
        """Mean episode return; deterministic=True rolls the policy
        MEAN (no exploration noise) so pre-vs-post adaptation
        comparisons aren't drowned by sampling variance."""
        if not deterministic:
            return float(self._collect(params,
                                       env_config)["mean_reward"])
        cfg = self.cfg
        env = cfg["env"](dict(env_config, horizon=cfg["horizon"]))
        obs, _ = env.reset(seed=0)
        total = 0.0
        for _ in range(cfg["horizon"]):
            mean, _ = self._forward(
                params, jnp.asarray(obs, jnp.float32)[None])
            obs, r, term, trunc, _ = env.step(np.asarray(mean)[0])
            total += r
            if term or trunc:
                break
        return float(total)

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params),
                "iter": self._iter}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self._iter = data.get("iter", 0)
