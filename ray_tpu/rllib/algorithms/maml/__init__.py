from ray_tpu.rllib.algorithms.maml.maml import MAML, MAMLConfig, PointGoalEnv  # noqa: F401
