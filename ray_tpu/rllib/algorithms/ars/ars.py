"""ARS: augmented random search — derivative-free linear/MLP policy
search with the three ARS augmentations over vanilla random search:
(1) divide the update by the std of the collected returns, (2) use only
the top-k best perturbation directions, (3) normalize observations with
running mean/std shared across evaluations.

Reference: rllib/algorithms/ars/ars.py (Workers evaluate mirrored noise
deltas; ars.py:~train collects top-`num_top` directions and scales the
step by the return std; observation filtering via MeanStdFilter).
Re-designed like our ES: evaluations are stateless remote tasks (the
seed regenerates the noise), and the running obs filter is folded on the
driver from per-task sufficient statistics instead of a filter actor.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.es.es import (_episode_return, _mlp_shapes,
                                            _unflatten)
from ray_tpu.tune.trainable import Trainable


class _RunningStat:
    """Mean/std over all observations seen (reference:
    utils/filter.py MeanStdFilter sufficient statistics)."""

    def __init__(self, dim: int):
        self.n = 0
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)

    def merge(self, n: int, mean: np.ndarray, m2: np.ndarray):
        if n == 0:
            return
        delta = mean - self.mean
        tot = self.n + n
        self.mean += delta * n / tot
        self.m2 += m2 + delta * delta * self.n * n / tot
        self.n = tot

    def std(self) -> np.ndarray:
        if self.n < 2:
            return np.ones_like(self.mean)
        return np.sqrt(np.maximum(self.m2 / (self.n - 1), 1e-8))


def _normed_episode(layers, env, max_steps: int, seed: int,
                    mean: np.ndarray, std: np.ndarray):
    """Episode with observation normalization; returns (ret, steps,
    obs-sum, obs-sumsq) so the driver can fold the filter."""
    obs, _ = env.reset(seed=seed)
    total, steps = 0.0, 0
    s = np.zeros_like(mean)
    ss = np.zeros_like(mean)
    for _ in range(max_steps):
        s += obs
        ss += obs * obs
        from ray_tpu.rllib.algorithms.es.es import _mlp_act
        a = _mlp_act(layers, (obs - mean) / std)
        obs, reward, terminated, truncated, _ = env.step(a)
        total += float(reward)
        steps += 1
        if terminated or truncated:
            break
    return total, steps, s, ss


def _ars_eval(flat_params: np.ndarray, noise_seed: int, sigma: float,
              env_name: str, env_config: Dict, shapes,
              max_steps: int, mean: np.ndarray, std: np.ndarray):
    """Evaluate one mirrored delta pair under the frozen obs filter;
    ships back per-direction returns plus obs sufficient stats."""
    import gymnasium as gym
    rng = np.random.RandomState(noise_seed)
    eps = rng.randn(flat_params.size).astype(np.float32)
    env = gym.make(env_name, **(env_config or {}))
    rets, steps = [], 0
    s = np.zeros_like(mean)
    ss = np.zeros_like(mean)
    count = 0
    for sign in (1.0, -1.0):
        layers = _unflatten(flat_params + sign * sigma * eps, shapes)
        ret, n, es_, ess = _normed_episode(
            layers, env, max_steps, seed=noise_seed * 1000 + int(sign),
            mean=mean, std=std)
        rets.append(ret)
        steps += n
        s += es_
        ss += ess
        count += n
    env.close()
    return noise_seed, rets[0], rets[1], steps, count, s, ss


class ARSConfig:
    def __init__(self):
        self.algo_class = ARS
        self._config: Dict = {
            "env": "CartPole-v1",
            "env_config": {},
            "num_deltas": 16,        # mirrored pairs per iteration
            "num_top": 8,            # directions kept for the update
            "sigma": 0.05,
            "lr": 0.02,
            "max_episode_steps": 500,
            "fcnet_hiddens": (),     # ARS default: LINEAR policy
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "ARSConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "ARSConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "ARSConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "ARS":
        return ARS(config=self.to_dict())


class ARS(Trainable):
    """Each train() = one ARS-V2 step (top directions + return-std
    scaling + running obs normalization)."""

    def setup(self, config: Dict):
        defaults = ARSConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        import gymnasium as gym
        env = gym.make(self.cfg["env"], **self.cfg["env_config"])
        obs_dim = int(np.prod(env.observation_space.shape))
        num_actions = int(env.action_space.n)
        env.close()
        self.shapes = _mlp_shapes(obs_dim, num_actions,
                                  tuple(self.cfg["fcnet_hiddens"]))
        n = sum(i * o + o for i, o in self.shapes)
        self.flat_params = np.zeros(n, np.float32)  # ARS: start at 0
        self.filter = _RunningStat(obs_dim)
        self._eval_task = ray_tpu.remote(_ars_eval)
        self._next_seed = self.cfg["seed"] * 100_000 + 1
        self._timesteps_total = 0

    def step(self) -> Dict:
        cfg = self.cfg
        seeds = [self._next_seed + i for i in range(cfg["num_deltas"])]
        self._next_seed += cfg["num_deltas"]
        mean = self.filter.mean.copy()
        std = self.filter.std()
        params_ref = ray_tpu.put(self.flat_params)
        refs = [self._eval_task.remote(
            params_ref, s, cfg["sigma"], cfg["env"], cfg["env_config"],
            self.shapes, cfg["max_episode_steps"], mean, std)
            for s in seeds]
        results = ray_tpu.get(refs, timeout=600)

        # Fold obs statistics AFTER the rollouts (the filter used inside
        # an iteration stays frozen — reference keeps per-iteration
        # filter sync too).
        for _, _, _, steps, count, s, ss in results:
            self._timesteps_total += steps
            if count:
                m = s / count
                self.filter.merge(count, m, ss - count * m * m)

        # Keep only the top `num_top` directions by max(r+, r-).
        scored = sorted(results,
                        key=lambda r: max(r[1], r[2]), reverse=True)
        top = scored[:cfg["num_top"]]
        used_rets = np.array([[rp, rn] for _, rp, rn, _, _, _, _ in top],
                             np.float32)
        sigma_r = max(float(used_rets.std()), 1e-6)

        grad = np.zeros_like(self.flat_params)
        for (seed, rp, rn, *_rest) in top:
            rng = np.random.RandomState(seed)
            eps = rng.randn(self.flat_params.size).astype(np.float32)
            grad += (rp - rn) * eps
        self.flat_params = (
            self.flat_params
            + cfg["lr"] / (cfg["num_top"] * sigma_r) * grad
        ).astype(np.float32)

        # Evaluate the unperturbed policy under the updated filter.
        import gymnasium as gym
        env = gym.make(cfg["env"], **cfg["env_config"])
        layers = _unflatten(self.flat_params, self.shapes)
        eval_ret, _, _, _ = _normed_episode(
            layers, env, cfg["max_episode_steps"],
            seed=int(self._next_seed), mean=self.filter.mean.copy(),
            std=self.filter.std())
        env.close()
        return {"episode_reward_mean": eval_ret,
                "pop_reward_mean": float(
                    np.mean([[rp, rn] for _, rp, rn, *_ in results])),
                "return_std_used": sigma_r,
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        return {"flat_params": self.flat_params,
                "filter": (self.filter.n, self.filter.mean,
                           self.filter.m2),
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.flat_params = data["flat_params"]
            n, mean, m2 = data["filter"]
            self.filter.n, self.filter.mean, self.filter.m2 = n, mean, m2
            self._timesteps_total = data.get("timesteps_total", 0)
