from ray_tpu.rllib.algorithms.ars.ars import ARS, ARSConfig  # noqa: F401
