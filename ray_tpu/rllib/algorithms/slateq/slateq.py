"""SlateQ: slate-based recommendation Q-learning via item-level
decomposition.

Reference: rllib/algorithms/slateq/slateq.py — the slate Q value
decomposes into per-item Q values weighted by the user choice model
(`Q(s, slate) = sum_i P(click i | slate) * Q(s, i)`), so learning stays
tractable in the item space while slates are built greedily by choice-
weighted item score.  TD updates use SARSA on the *served* next slate
(on-policy decomposition, slateq.py "SARSA" learning method).

Re-designed jax-first: the item scorer is a jitted (user, doc) -> Q
network evaluated on all candidates in one batched forward; the toy
interest-evolution env lives in env/recsim.py.
"""

from __future__ import annotations

from itertools import combinations
from typing import Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.env.recsim import InterestEvolutionRecSimEnv
from ray_tpu.tune.trainable import Trainable


class _ItemQNet(nn.Module):
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, user, doc):
        h = jnp.concatenate([user, doc], axis=-1)
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return nn.Dense(1)(h)[..., 0]


class SlateQConfig:
    def __init__(self):
        self.algo_class = SlateQ
        self._config: Dict = {
            "env_config": {},
            "lr": 1e-3,
            "gamma": 0.95,
            "train_batch_size": 32,     # transitions per SGD step
            "num_sgd_steps": 20,
            "episodes_per_iter": 8,
            "buffer_capacity": 10_000,
            "target_update_freq": 2,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_anneal_iters": 10,
            "fcnet_hiddens": (64, 64),
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "SlateQConfig":
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "SlateQConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "SlateQConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "SlateQ":
        return SlateQ(config=self.to_dict())


class SlateQ(Trainable):
    """Self-contained trainer (the slate action space doesn't fit the
    discrete/Box RolloutWorker row schema, so sampling lives here)."""

    def setup(self, config: Dict):
        defaults = SlateQConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        self.env = InterestEvolutionRecSimEnv(
            dict(self.cfg["env_config"], seed=self.cfg["seed"]))
        self.k = self.env.slate_size
        self.d = self.env.topic_dim
        self.n_docs = self.env.num_docs
        self.qnet = _ItemQNet(hiddens=tuple(self.cfg["fcnet_hiddens"]))
        rng = jax.random.PRNGKey(self.cfg["seed"])
        zu = jnp.zeros((1, self.d), jnp.float32)
        zd = jnp.zeros((1, self.d + 1), jnp.float32)
        self.params = self.qnet.init(rng, zu, zd)
        self.target_params = self.params
        self.tx = optax.adam(self.cfg["lr"])
        self.opt_state = self.tx.init(self.params)
        self._forward = jax.jit(self.qnet.apply)
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._buffer: List[Dict] = []
        self._iter = 0
        self._timesteps_total = 0
        self._episode_rewards: List[float] = []

    # ------------------------------------------------- slate construction
    def _split_obs(self, obs: np.ndarray):
        user = obs[:self.d]
        docs = obs[self.d:].reshape(self.n_docs, self.d + 1)
        return user, docs

    def _item_q(self, params, user, docs) -> np.ndarray:
        u = jnp.broadcast_to(jnp.asarray(user, jnp.float32),
                             (self.n_docs, self.d))
        return np.asarray(self._forward(params, u,
                                        jnp.asarray(docs, jnp.float32)))

    def _best_slate(self, params, user, docs):
        """Exact slate maximization of sum_i P(i|slate) Q_i over all
        C(n, k) slates (reference slateq's optimizer for small n; the
        toy env keeps n small so exact search is cheap)."""
        q = self._item_q(params, user, docs)
        scores = docs[:, :self.d] @ user          # choice-model logits
        best, best_val = None, -np.inf
        for slate in combinations(range(self.n_docs), self.k):
            s = np.asarray(slate)
            logits = np.append(scores[s], self.env.no_click_logit)
            e = np.exp(logits - logits.max())
            p = e / e.sum()
            val = float((p[:-1] * q[s]).sum())
            if val > best_val:
                best, best_val = s, val
        return best, best_val

    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._iter / max(cfg["epsilon_anneal_iters"], 1))
        return (cfg["initial_epsilon"]
                + frac * (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    # ---------------------------------------------------------- sampling
    def _run_episode(self, eps: float) -> float:
        obs, _ = self.env.reset(seed=int(self._rng.randint(2**31)))
        total = 0.0
        done = False
        while not done:
            user, docs = self._split_obs(obs)
            if self._rng.rand() < eps:
                slate = self._rng.choice(self.n_docs, self.k,
                                         replace=False)
            else:
                slate, _ = self._best_slate(self.params, user, docs)
            obs2, reward, done, _, info = self.env.step(slate)
            self._buffer.append({
                "user": user, "docs": docs, "slate": np.asarray(slate),
                "clicked": info["clicked"], "reward": float(reward),
                "next_obs": obs2, "done": done})
            if len(self._buffer) > self.cfg["buffer_capacity"]:
                self._buffer.pop(0)
            total += reward
            self._timesteps_total += 1
            obs = obs2
        return total

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, params, opt_state, user, doc, target):
        def loss_fn(p):
            q = self.qnet.apply(p, user, doc)
            return ((q - target) ** 2).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        eps = self._epsilon()
        rets = [self._run_episode(eps)
                for _ in range(cfg["episodes_per_iter"])]
        self._episode_rewards += rets

        # SARSA-style TD on clicked transitions: target = r + gamma *
        # slate-value of the next state's best slate under the TARGET
        # net (the decomposed E[Q] — slateq.py's next-slate value).
        loss = np.nan
        clicked = [t for t in self._buffer if t["clicked"] is not None]
        for _ in range(cfg["num_sgd_steps"]):
            if len(clicked) < cfg["train_batch_size"]:
                break
            idx = self._rng.randint(0, len(clicked),
                                    cfg["train_batch_size"])
            users, docs, targets = [], [], []
            for i in idx:
                t = clicked[i]
                doc_row = t["docs"][t["clicked"]]
                next_v = 0.0
                if not t["done"]:
                    nu, nd = self._split_obs(t["next_obs"])
                    _, next_v = self._best_slate(self.target_params,
                                                 nu, nd)
                users.append(t["user"])
                docs.append(doc_row)
                targets.append(t["reward"] + cfg["gamma"] * next_v)
            self.params, self.opt_state, jloss = self._train_step(
                self.params, self.opt_state,
                jnp.asarray(np.stack(users)),
                jnp.asarray(np.stack(docs)),
                jnp.asarray(np.asarray(targets, np.float32)))
            loss = float(jloss)
        if self._iter % cfg["target_update_freq"] == 0:
            self.target_params = self.params

        recent = self._episode_rewards[-50:]
        return {"episode_reward_mean": float(np.mean(recent)),
                "episode_reward_this_iter": float(np.mean(rets)),
                "td_loss": loss, "epsilon": eps,
                "buffer_clicked": len(clicked),
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params),
                "iter": self._iter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self.target_params = self.params
            self._iter = data.get("iter", 0)
            self._timesteps_total = data.get("timesteps_total", 0)
