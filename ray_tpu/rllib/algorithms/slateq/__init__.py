from ray_tpu.rllib.algorithms.slateq.slateq import (  # noqa: F401
    SlateQ,
    SlateQConfig,
)
