from ray_tpu.rllib.algorithms.qmix.qmix import QMix, QMixConfig  # noqa: F401
