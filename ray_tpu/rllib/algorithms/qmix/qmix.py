"""QMIX: cooperative multi-agent Q-learning with a monotonic mixing
network.

Reference: rllib/algorithms/qmix/qmix.py (+ qmix_policy.py's QMixer) —
per-agent utility networks Q_i(o_i, a_i) are combined into a joint
Q_tot(s, a) by a hypernetwork-generated mixer whose weights are
constrained non-negative, so argmax decomposes per agent while credit
assignment uses the centralized state.  Re-derived jax-first: agent
nets (parameter-shared with an agent-id one-hot, the standard QMIX
trick) and the mixer train end-to-end in one jitted TD step.

Works on any `MultiAgentEnv` whose team is fixed (all agents act every
step); the global state is `env.state()` when defined, else the
concatenation of agent observations.
"""

from __future__ import annotations

from typing import Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class _AgentQNet(nn.Module):
    num_actions: int
    hiddens: tuple = (64,)

    @nn.compact
    def __call__(self, obs):
        h = obs
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return nn.Dense(self.num_actions)(h)


class _Mixer(nn.Module):
    """Monotonic mixer: Q_tot = w2(s)·elu(w1(s)·q + b1(s)) + b2(s) with
    w1, w2 >= 0 via abs (reference qmix_policy.QMixer)."""

    n_agents: int
    embed: int = 32

    @nn.compact
    def __call__(self, qs, state):
        B = qs.shape[0]
        w1 = jnp.abs(nn.Dense(self.n_agents * self.embed)(state))
        w1 = w1.reshape(B, self.n_agents, self.embed)
        b1 = nn.Dense(self.embed)(state)
        hidden = nn.elu(jnp.einsum("ba,bae->be", qs, w1) + b1)
        w2 = jnp.abs(nn.Dense(self.embed)(state))
        b2 = nn.Dense(1)(nn.relu(nn.Dense(self.embed)(state)))[..., 0]
        return (hidden * w2).sum(-1) + b2


class QMixConfig:
    def __init__(self):
        self.algo_class = QMix
        self._config: Dict = {
            "env": None,            # MultiAgentEnv subclass or creator
            "env_config": {},
            "lr": 5e-4,
            "gamma": 0.99,
            "mixing_embed_dim": 32,
            "buffer_capacity": 5000,
            "train_batch_size": 32,
            "num_sgd_steps": 40,
            "episodes_per_iter": 16,
            "target_update_freq": 4,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_anneal_iters": 12,
            "fcnet_hiddens": (64,),
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "QMixConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "QMixConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "QMixConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "QMix":
        return QMix(config=self.to_dict())


class QMix(Trainable):
    def setup(self, config: Dict):
        defaults = QMixConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        from ray_tpu.rllib.env.registry import resolve_env_creator
        self.env = resolve_env_creator(self.cfg["env"])(
            self.cfg["env_config"])
        self.agents = list(self.env.possible_agents)
        self.n_agents = len(self.agents)
        obs_space = self.env.observation_space(self.agents[0])
        self.obs_dim = int(np.prod(obs_space.shape))
        self.num_actions = int(self.env.action_space(self.agents[0]).n)
        # Input = obs ++ one-hot agent id (parameter sharing).
        in_dim = self.obs_dim + self.n_agents
        self.agent_net = _AgentQNet(
            num_actions=self.num_actions,
            hiddens=tuple(self.cfg["fcnet_hiddens"]))
        self.env.reset(seed=self.cfg["seed"])  # state() needs live env
        state_dim = (int(np.prod(np.shape(self.env.state())))
                     if hasattr(self.env, "state")
                     else self.obs_dim * self.n_agents)
        self.mixer = _Mixer(n_agents=self.n_agents,
                            embed=self.cfg["mixing_embed_dim"])
        rng = jax.random.PRNGKey(self.cfg["seed"])
        k1, k2 = jax.random.split(rng)
        self.params = {
            "agent": self.agent_net.init(
                k1, jnp.zeros((1, in_dim), jnp.float32)),
            "mixer": self.mixer.init(
                k2, jnp.zeros((1, self.n_agents), jnp.float32),
                jnp.zeros((1, state_dim), jnp.float32)),
        }
        self.target_params = self.params
        self.tx = optax.adam(self.cfg["lr"])
        self.opt_state = self.tx.init(self.params)
        self._agent_forward = jax.jit(self.agent_net.apply)
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._eye = np.eye(self.n_agents, dtype=np.float32)
        self._buffer: List[Dict] = []
        self._iter = 0
        self._timesteps_total = 0
        self._episode_rewards: List[float] = []

    # ---------------------------------------------------------- plumbing
    def _state(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        if hasattr(self.env, "state"):
            return np.asarray(self.env.state(), np.float32).reshape(-1)
        return np.concatenate([np.asarray(obs[a], np.float32).reshape(-1)
                               for a in self.agents])

    def _stack_obs(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        """(n_agents, obs_dim + n_agents) with agent-id one-hots."""
        rows = [np.concatenate([
            np.asarray(obs[a], np.float32).reshape(-1), self._eye[i]])
            for i, a in enumerate(self.agents)]
        return np.stack(rows)

    def _act(self, obs: Dict, eps: float) -> Dict[str, int]:
        q = np.asarray(self._agent_forward(
            self.params["agent"], jnp.asarray(self._stack_obs(obs))))
        actions = {}
        for i, a in enumerate(self.agents):
            if self._rng.rand() < eps:
                actions[a] = int(self._rng.randint(self.num_actions))
            else:
                actions[a] = int(q[i].argmax())
        return actions

    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._iter / max(cfg["epsilon_anneal_iters"], 1))
        return (cfg["initial_epsilon"]
                + frac * (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    # ---------------------------------------------------------- sampling
    def _run_episode(self, eps: float) -> float:
        obs, _ = self.env.reset(seed=int(self._rng.randint(2**31)))
        total = 0.0
        done = False
        while not done:
            state = self._state(obs)
            actions = self._act(obs, eps)
            obs2, rews, terms, truncs, _ = self.env.step(actions)
            done = terms.get("__all__", False) or truncs.get("__all__",
                                                             False)
            reward = float(sum(rews.values()))  # cooperative team reward
            self._buffer.append({
                "obs": self._stack_obs(obs), "state": state,
                "actions": np.asarray([actions[a] for a in self.agents],
                                      np.int32),
                "reward": reward, "done": done,
                "next_obs": (self._stack_obs(obs2) if obs2
                             else self._stack_obs(obs)),
                "next_state": (self._state(obs2) if obs2 else state)})
            if len(self._buffer) > self.cfg["buffer_capacity"]:
                self._buffer.pop(0)
            total += reward
            self._timesteps_total += 1
            obs = obs2 if obs2 else obs
        return total

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, params, target_params, opt_state, batch):
        gamma = self.cfg["gamma"]

        def loss_fn(p):
            B, n, _ = batch["obs"].shape
            q_all = self.agent_net.apply(
                p["agent"], batch["obs"].reshape(B * n, -1)
            ).reshape(B, n, -1)
            qa = jnp.take_along_axis(
                q_all, batch["actions"][..., None], axis=-1)[..., 0]
            q_tot = self.mixer.apply(p["mixer"], qa, batch["state"])

            tq_all = self.agent_net.apply(
                target_params["agent"],
                batch["next_obs"].reshape(B * n, -1)).reshape(B, n, -1)
            # Monotonicity => joint argmax decomposes per agent.
            tqa = tq_all.max(axis=-1)
            t_tot = self.mixer.apply(target_params["mixer"], tqa,
                                     batch["next_state"])
            target = batch["reward"] + gamma * t_tot * (
                1.0 - batch["done"].astype(jnp.float32))
            return ((q_tot - jax.lax.stop_gradient(target)) ** 2).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        eps = self._epsilon()
        rets = [self._run_episode(eps)
                for _ in range(cfg["episodes_per_iter"])]
        self._episode_rewards += rets
        loss = np.nan
        for _ in range(cfg["num_sgd_steps"]):
            if len(self._buffer) < cfg["train_batch_size"]:
                break
            idx = self._rng.randint(0, len(self._buffer),
                                    cfg["train_batch_size"])
            cols = {k: jnp.asarray(np.stack(
                [self._buffer[i][k] for i in idx]))
                for k in ("obs", "state", "actions", "reward", "done",
                          "next_obs", "next_state")}
            self.params, self.opt_state, jloss = self._train_step(
                self.params, self.target_params, self.opt_state, cols)
            loss = float(jloss)
        if self._iter % cfg["target_update_freq"] == 0:
            self.target_params = self.params
        recent = self._episode_rewards[-100:]
        return {"episode_reward_mean": float(np.mean(recent)),
                "episode_reward_this_iter": float(np.mean(rets)),
                "td_loss": loss, "epsilon": eps,
                "timesteps_total": self._timesteps_total}

    def greedy_actions(self, obs: Dict) -> Dict[str, int]:
        """Deterministic joint action (for tests/eval)."""
        return self._act(obs, eps=0.0)

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "iter": self._iter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self.target_params = self.params
            self._iter = data.get("iter", 0)
            self._timesteps_total = data.get("timesteps_total", 0)
