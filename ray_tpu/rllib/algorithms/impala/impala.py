"""IMPALA: async rollouts feeding a learner thread.

Reference: rllib/algorithms/impala/impala.py:445 (learner thread wiring
:349) — rollout workers sample continuously; ready batches stream into
the LearnerThread; weights broadcast on a cadence, so learning and
sampling overlap instead of alternating as in PPO.
"""

from __future__ import annotations

from typing import Dict, List

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.execution.learner_thread import LearnerThread


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(Impala)
        self._config.update({
            "loss": "impala",
            "rho_clip": 1.0,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "broadcast_interval": 1,   # batches between weight pushes
            "min_steps_per_iteration": 1000,
        })


class Impala(Algorithm):
    def _extra_defaults(self) -> Dict:
        return {"loss": "impala", "rho_clip": 1.0, "vf_loss_coeff": 0.5,
                "entropy_coeff": 0.01, "broadcast_interval": 1,
                "min_steps_per_iteration": 1000}

    def setup(self, config: Dict):
        super().setup(config)
        self.learner = LearnerThread(self.workers.local_worker.policy)
        self.learner.start()
        frag = self.algo_config["rollout_fragment_length"]
        # Prime one in-flight sample per worker.
        self._inflight = {w.sample.remote(frag): w
                          for w in self.workers.remote_workers}
        self._since_broadcast = 0

    def training_step(self) -> Dict:
        cfg = self.algo_config
        frag = cfg["rollout_fragment_length"]
        steps_this_iter = 0
        # Drain ready rollouts into the learner while keeping every worker
        # busy (the async loop of impala.py:445).
        while steps_this_iter < cfg["min_steps_per_iteration"]:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=60.0)
            for ref in ready:
                worker = self._inflight.pop(ref)
                batch = ray_tpu.get(ref, timeout=60)
                steps_this_iter += batch.count
                self._timesteps_total += batch.count
                self.learner.inqueue.put(batch)
                self._since_broadcast += 1
                if self._since_broadcast >= cfg["broadcast_interval"]:
                    self._since_broadcast = 0
                    wref = ray_tpu.put(self.learner.get_weights())
                    # Ordered before the next sample dispatch below.
                    worker.set_weights.remote(wref)  # noqa: RTL002
                self._inflight[worker.sample.remote(frag)] = worker
        return {"info": {"learner": dict(self.learner.stats),
                         "learner_queue_size": self.learner.inqueue.qsize(),
                         "num_batches_trained": self.learner.num_batches},
                "num_env_steps_trained": self.learner.num_steps_trained}

    def cleanup(self):
        self.learner.stop()
        super().cleanup()
