from ray_tpu.rllib.algorithms.impala.impala import Impala, ImpalaConfig  # noqa: F401
