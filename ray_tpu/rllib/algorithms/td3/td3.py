"""TD3: twin delayed DDPG (Fujimoto et al. 2018).

Reference: rllib/algorithms/td3/td3.py — in the reference, TD3 IS a DDPG
config preset: twin clipped-double-Q critics, delayed policy/target
updates, and target-policy smoothing noise.  Mirrored here the same way;
the mechanics live in policy/jax_ddpg_policy.py.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPG, DDPGConfig


class TD3Config(DDPGConfig):
    def __init__(self):
        super().__init__(TD3)
        self._config.update({
            "twin_q": True,
            "policy_delay": 2,
            "target_noise": 0.2,
            "target_noise_clip": 0.5,
            "exploration_noise": 0.1,
        })


class TD3(DDPG):
    def _extra_defaults(self):
        return dict(TD3Config()._config)
