from ray_tpu.rllib.algorithms.td3.td3 import TD3, TD3Config  # noqa: F401
