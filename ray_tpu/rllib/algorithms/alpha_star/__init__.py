from ray_tpu.rllib.algorithms.alpha_star.alpha_star import (  # noqa: F401
    AlphaStar,
    AlphaStarConfig,
)
