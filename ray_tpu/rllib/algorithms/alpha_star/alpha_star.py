"""AlphaStar-style league training (Vinyals et al. 2019), scoped.

Reference: rllib/algorithms/alpha_star/alpha_star.py — the contribution
over plain self-play is the LEAGUE: a population of frozen snapshots
plus three live roles — main agents (train against a prioritized
fictitious self-play mixture of the whole league), main exploiters
(train only against the current main agent, finding its weaknesses),
and league exploiters (train against the league mixture) — with
win-rate-driven PFSP matchmaking and periodic snapshotting.  Plain
self-play famously CYCLES on games with rock-paper-scissors structure;
the league converges toward the Nash mixture.

Scoped re-design: the "game" is any symmetric zero-sum matrix game
(default: rock-paper-scissors), policies are softmax logit vectors
trained by REINFORCE against sampled opponents, and exploitability
(max_a E[payoff(a, pi)]) is computed exactly — the property the league
exists to minimize.  The league MACHINERY (roles, PFSP, snapshots,
payoff table) is the reference-parity surface; the game is the smallest
one with the pathology that motivates it.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ray_tpu.tune.trainable import Trainable

RPS_PAYOFF = np.array([[0.0, -1.0, 1.0],
                       [1.0, 0.0, -1.0],
                       [-1.0, 1.0, 0.0]])


def _softmax(x):
    e = np.exp(x - x.max())
    return e / e.sum()


class _LeagueMember:
    __slots__ = ("logits", "role", "frozen")

    def __init__(self, logits, role, frozen=False):
        self.logits = logits.astype(np.float64)
        self.role = role       # main | main_exploiter | league_exploiter
        self.frozen = frozen

    def policy(self):
        return _softmax(self.logits)


class AlphaStarConfig:
    def __init__(self):
        self.algo_class = AlphaStar
        self._config: Dict = {
            "payoff_matrix": RPS_PAYOFF,
            "lr": 0.3,
            "games_per_step": 512,
            "num_main": 1,
            "num_main_exploiters": 1,
            "num_league_exploiters": 1,
            "snapshot_every": 2,     # iterations between league freezes
            "pfsp_power": 2.0,       # hard-opponent weighting exponent
            "init_scale": 0.1,       # initial logit spread (big = far
                                     # from Nash, shows league value)
            "seed": 0,
        }

    def training(self, **kwargs) -> "AlphaStarConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "AlphaStarConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "AlphaStar":
        return AlphaStar(config=self.to_dict())


class AlphaStar(Trainable):
    def setup(self, config: Dict):
        defaults = AlphaStarConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        self.A = np.asarray(self.cfg["payoff_matrix"], np.float64)
        self.n_actions = self.A.shape[0]
        self._rng = np.random.RandomState(self.cfg["seed"])
        self.league: List[_LeagueMember] = []
        for _ in range(self.cfg["num_main"]):
            self.league.append(self._spawn("main"))
        for _ in range(self.cfg["num_main_exploiters"]):
            self.league.append(self._spawn("main_exploiter"))
        for _ in range(self.cfg["num_league_exploiters"]):
            self.league.append(self._spawn("league_exploiter"))
        self._iter = 0

    def _spawn(self, role) -> _LeagueMember:
        return _LeagueMember(
            self._rng.randn(self.n_actions) * self.cfg["init_scale"],
            role)

    # ------------------------------------------------------ matchmaking
    def _live(self, role=None):
        return [m for m in self.league
                if not m.frozen and (role is None or m.role == role)]

    def _pfsp_opponent(self, agent) -> "_LeagueMember":
        """Prioritized fictitious self-play (reference: pfsp weighting):
        main agents face the WHOLE league weighted toward opponents they
        LOSE to; main exploiters face only the current main agent;
        league exploiters face the league uniformly."""
        if agent.role == "main_exploiter":
            mains = self._live("main")
            return mains[self._rng.randint(len(mains))]
        pool = [m for m in self.league if m is not agent]
        if not pool:
            return agent  # degenerate league: plain self-play
        if agent.role == "league_exploiter":
            return pool[self._rng.randint(len(pool))]
        # main: PFSP — weight by (1 - winrate vs opponent)^power.
        w = []
        p_a = agent.policy()
        for m in pool:
            ev = p_a @ self.A @ m.policy()     # expected payoff in [-1,1]
            winrate = (ev + 1.0) / 2.0
            w.append((1.0 - winrate) ** self.cfg["pfsp_power"] + 1e-3)
        w = np.asarray(w)
        return pool[self._rng.choice(len(pool), p=w / w.sum())]

    # ------------------------------------------------------ learning
    def _reinforce(self, agent, opponent):
        """One REINFORCE game batch of agent vs opponent."""
        n = self.cfg["games_per_step"]
        p = agent.policy()
        q = opponent.policy()
        a = self._rng.choice(self.n_actions, n, p=p)
        b = self._rng.choice(self.n_actions, n, p=q)
        payoff = self.A[a, b]
        baseline = payoff.mean()
        grad = np.zeros(self.n_actions)
        for i in range(n):
            g = np.zeros(self.n_actions)
            g[a[i]] = 1.0
            grad += (payoff[i] - baseline) * (g - p)
        agent.logits += self.cfg["lr"] * grad / n
        return baseline

    def exploitability(self, member: Optional[_LeagueMember] = None
                       ) -> float:
        """max_a E_b~pi [payoff(a, b)] — 0 at the Nash mixture."""
        m = member or self._live("main")[0]
        return float((self.A @ m.policy()).max())

    def league_mixture(self) -> np.ndarray:
        """The league's average policy (main lineage + snapshots) — the
        fictitious-self-play object that converges to Nash in zero-sum
        games; single members may cycle forever (the RPS pathology),
        the MIXTURE is what the league makes strong."""
        mains = [m for m in self.league
                 if m.role == "main"]
        return np.mean([m.policy() for m in mains], axis=0)

    def mixture_exploitability(self) -> float:
        return float((self.A @ self.league_mixture()).max())

    def step(self) -> Dict:
        self._iter += 1
        evs = {}
        for agent in self._live():
            opp = self._pfsp_opponent(agent)
            evs[agent.role] = self._reinforce(agent, opp)
        if self._iter % self.cfg["snapshot_every"] == 0:
            # Freeze copies of every live agent into the league
            # (reference: past-player snapshots the PFSP pool draws on).
            for agent in list(self._live()):
                snap = _LeagueMember(agent.logits.copy(), agent.role,
                                     frozen=True)
                self.league.append(snap)
        main = self._live("main")[0]
        mix_expl = self.mixture_exploitability()
        return {"exploitability": self.exploitability(main),
                "mixture_exploitability": mix_expl,
                "main_policy": main.policy().tolist(),
                "league_size": len(self.league),
                "episode_reward_mean": -mix_expl,
                "training_iteration_": self._iter}

    def save_checkpoint(self) -> Dict:
        return {"league": [(m.logits, m.role, m.frozen)
                           for m in self.league],
                "iter": self._iter}

    def load_checkpoint(self, data) -> None:
        if data:
            self.league = [_LeagueMember(lg, role, frozen)
                           for lg, role, frozen in data["league"]]
            self._iter = data.get("iter", 0)
