"""A2C: synchronous advantage actor-critic.

Reference: rllib/algorithms/a2c/a2c.py (training_step = sync sample ->
one SGD pass -> broadcast; A2C is A3C's synchronous form, see
rllib/algorithms/a3c/a3c.py for the loss) — re-derived jax-first: the
vanilla policy-gradient loss is one jitted value_and_grad step on the
learner, rollouts ride the CPU actor gang.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import sample_batch as sb
from ray_tpu.rllib.policy.jax_policy import JaxPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class A2CPolicy(JaxPolicy):
    """Plain advantage actor-critic loss (no ratio clipping): the
    on-policy gradient -logp(a|s) * A with a value-function head and
    entropy bonus (reference: a3c loss in
    rllib/algorithms/a3c/a3c_torch_policy.py)."""

    def _loss(self, params, batch):
        cfg = self.config
        logits, value = self.model.apply(params, batch[sb.OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch[sb.ACTIONS]]
        adv = batch[sb.ADVANTAGES]
        pg_loss = -(logp * adv).mean()
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        vf_loss = ((value - batch[sb.VALUE_TARGETS]) ** 2).mean()
        total = (pg_loss
                 + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                 - cfg.get("entropy_coeff", 0.01) * entropy.mean())
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy.mean()}


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(A2C)
        self._config.update({
            "lr": 1e-3,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "microbatch_size": 0,  # 0 = single pass over the full batch
        })


class A2C(Algorithm):
    policy_cls = A2CPolicy

    def _extra_defaults(self) -> Dict:
        return {"lr": 1e-3, "vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
                "microbatch_size": 0}

    def training_step(self) -> Dict:
        """Sync sample across the gang, one gradient pass, broadcast
        (reference a2c.py training_step; microbatching optional)."""
        cfg = self.algo_config
        target = cfg["train_batch_size"]
        per_worker = max(1, target
                         // max(1, len(self.workers.remote_workers)))
        batches = []
        collected = 0
        while collected < target:
            refs = self.workers.sample_all(per_worker)
            if not refs:
                b = self.workers.local_worker.sample(per_worker)
                batches.append(b)
                collected += b.count
                continue
            for b in ray_tpu.get(refs, timeout=600):
                batches.append(b)
                collected += b.count
        train_batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += train_batch.count

        adv = train_batch[sb.ADVANTAGES]
        train_batch[sb.ADVANTAGES] = (
            (adv - adv.mean()) / max(adv.std(), 1e-6)).astype(np.float32)

        policy = self.workers.local_worker.policy
        mb = cfg["microbatch_size"] or train_batch.count
        stats: Dict = {}
        for minibatch in train_batch.minibatches(min(mb,
                                                     train_batch.count)):
            stats = policy.learn_on_batch(minibatch)

        self.workers.sync_weights()
        return {"info": {"learner": stats},
                "num_env_steps_trained": train_batch.count}
