"""PG: vanilla policy gradient (REINFORCE with a value baseline via
GAE's lam=1 degenerate form).

Reference: rllib/algorithms/pg/pg.py — the minimal on-policy algorithm:
sample synchronously, one gradient step on -logp * advantage.  lambda=1
makes GAE degenerate to Monte Carlo returns minus the value baseline;
the shared A2C jitted loss runs with the entropy coefficient zeroed and
the vf coefficient kept for the baseline fit.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.rllib.algorithms.a2c.a2c import A2C
from ray_tpu.rllib.algorithms.algorithm import AlgorithmConfig


class PGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PG)
        self._config.update({
            "lr": 2e-3,
            "lambda": 1.0,          # GAE -> Monte Carlo returns
            "vf_loss_coeff": 0.5,   # baseline fit only
            "entropy_coeff": 0.0,
            "microbatch_size": 0,
        })


class PG(A2C):
    def _extra_defaults(self) -> Dict:
        return {"lr": 2e-3, "lambda": 1.0, "vf_loss_coeff": 0.5,
                "entropy_coeff": 0.0, "microbatch_size": 0}
