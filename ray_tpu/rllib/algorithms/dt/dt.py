"""DT: Decision Transformer — offline RL as return-conditioned sequence
modeling (Chen et al. 2021).

Reference: rllib/algorithms/dt/dt.py (+ dt_torch_model.py) — episodes
become token sequences (return-to-go, observation, action) * K; a causal
transformer is trained to predict the action at each observation token;
at evaluation the model is conditioned on a target return and rolled out
autoregressively, decrementing the return-to-go by observed rewards.

Re-derived jax-first: the model is a tiny pre-LN causal transformer
whose full training step (sampled-subsequence batch -> cross-entropy ->
adam) is one jitted function; evaluation reuses the same jitted forward
with a sliding K-window.
"""

from __future__ import annotations

from typing import Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class _Block(nn.Module):
    dim: int
    heads: int

    @nn.compact
    def __call__(self, x, mask):
        h = nn.LayerNorm()(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.heads, qkv_features=self.dim)(h, h, mask=mask)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(4 * self.dim)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim)(h)
        return x + h


class _DTModel(nn.Module):
    """Tokens per timestep: (rtg, obs, action); action predicted from
    the obs-token stream."""

    obs_dim: int
    num_actions: int
    context_len: int
    dim: int = 64
    heads: int = 4
    layers: int = 2

    @nn.compact
    def __call__(self, rtg, obs, actions):
        # rtg: (B, K, 1) obs: (B, K, obs_dim) actions: (B, K) int32
        B, K = rtg.shape[0], rtg.shape[1]
        t_emb = self.param("time_emb",
                           nn.initializers.normal(0.02),
                           (self.context_len, self.dim))[:K]
        e_r = nn.Dense(self.dim)(rtg) + t_emb
        e_s = nn.Dense(self.dim)(obs) + t_emb
        e_a = nn.Embed(self.num_actions + 1, self.dim)(actions) + t_emb
        # Interleave (r_1, s_1, a_1, r_2, ...) -> (B, 3K, dim).
        x = jnp.stack([e_r, e_s, e_a], axis=2).reshape(B, 3 * K,
                                                       self.dim)
        mask = nn.make_causal_mask(jnp.zeros((B, 3 * K)))
        for _ in range(self.layers):
            x = _Block(dim=self.dim, heads=self.heads)(x, mask)
        x = nn.LayerNorm()(x)
        # Obs tokens sit at positions 3t+1; their outputs predict a_t.
        s_out = x.reshape(B, K, 3, self.dim)[:, :, 1, :]
        return nn.Dense(self.num_actions)(s_out)  # (B, K, A)


class DTConfig:
    def __init__(self):
        self.algo_class = DT
        self._config: Dict = {
            "env": "CartPole-v1",
            "env_config": {},
            "context_len": 20,
            "embed_dim": 64,
            "num_heads": 4,
            "num_layers": 2,
            "lr": 1e-3,
            "train_batch_size": 64,
            "num_sgd_steps": 100,
            "target_return": 200.0,
            "num_eval_episodes": 5,
            "max_episode_steps": 500,
            "input_data": None,   # list of episode dicts (obs, actions,
                                  # rewards) or offline .json path
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "DTConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "DTConfig":
        self._config.update(kwargs)
        return self

    def offline_data(self, input_data) -> "DTConfig":
        self._config["input_data"] = input_data
        return self

    def debugging(self, seed=None) -> "DTConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "DT":
        return DT(config=self.to_dict())


class DT(Trainable):
    def setup(self, config: Dict):
        defaults = DTConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        import gymnasium as gym
        env = gym.make(self.cfg["env"], **self.cfg["env_config"])
        self.obs_dim = int(np.prod(env.observation_space.shape))
        self.num_actions = int(env.action_space.n)
        env.close()
        data = self.cfg["input_data"]
        if data is None:
            raise ValueError("DT needs config.offline_data([...]) — a "
                             "list of {obs, actions, rewards} episodes "
                             "or an offline .json path")
        if isinstance(data, str):
            self.episodes = self._episodes_from_json(data)
        else:
            self.episodes = list(data)
        # Precompute returns-to-go per episode.
        for ep in self.episodes:
            r = np.asarray(ep["rewards"], np.float32)
            ep["rtg"] = np.cumsum(r[::-1])[::-1].copy()
        K = self.cfg["context_len"]
        self.model = _DTModel(
            obs_dim=self.obs_dim, num_actions=self.num_actions,
            context_len=K, dim=self.cfg["embed_dim"],
            heads=self.cfg["num_heads"], layers=self.cfg["num_layers"])
        rng = jax.random.PRNGKey(self.cfg["seed"])
        self.params = self.model.init(
            rng, jnp.zeros((1, K, 1)), jnp.zeros((1, K, self.obs_dim)),
            jnp.zeros((1, K), jnp.int32))
        self.tx = optax.adam(self.cfg["lr"])
        self.opt_state = self.tx.init(self.params)
        self._forward = jax.jit(self.model.apply)
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._iter = 0

    @staticmethod
    def _episodes_from_json(path: str) -> List[Dict]:
        """Split offline SampleBatch files into episodes on done flags."""
        from ray_tpu.rllib.offline import read_sample_batches
        batch = read_sample_batches(path)
        eps, start = [], 0
        dones = np.asarray(batch["dones"])
        for i, d in enumerate(dones):
            if d or i == len(dones) - 1:
                eps.append({
                    "obs": np.asarray(batch["obs"][start:i + 1],
                                      np.float32),
                    "actions": np.asarray(batch["actions"][start:i + 1],
                                          np.int64),
                    "rewards": np.asarray(batch["rewards"][start:i + 1],
                                          np.float32)})
                start = i + 1
        return eps

    # ---------------------------------------------------------- training
    def _sample_batch(self):
        K = self.cfg["context_len"]
        B = self.cfg["train_batch_size"]
        rtg = np.zeros((B, K, 1), np.float32)
        obs = np.zeros((B, K, self.obs_dim), np.float32)
        acts = np.full((B, K), self.num_actions, np.int64)  # pad token
        tgt = np.zeros((B, K), np.int64)
        mask = np.zeros((B, K), np.float32)
        # Episodes sampled proportionally to length (reference dt
        # SegmentationBuffer's weighting).
        lens = np.asarray([len(e["rewards"]) for e in self.episodes],
                          np.float64)
        probs = lens / lens.sum()
        for b in range(B):
            ep = self.episodes[self._rng.choice(len(self.episodes),
                                                p=probs)]
            T = len(ep["rewards"])
            end = self._rng.randint(1, T + 1)      # inclusive end index
            start = max(0, end - K)
            L = end - start
            rtg[b, :L, 0] = ep["rtg"][start:end]
            obs[b, :L] = ep["obs"][start:end]
            tgt[b, :L] = ep["actions"][start:end]
            # Input actions are shifted: a_t is PREDICTED at s_t, so the
            # action token at t feeds step t+1; position t holds a_{t}
            # for the attention of later tokens (training uses teacher
            # forcing with the true actions).
            acts[b, :L] = ep["actions"][start:end]
            mask[b, :L] = 1.0
        return (jnp.asarray(rtg), jnp.asarray(obs), jnp.asarray(acts),
                jnp.asarray(tgt), jnp.asarray(mask))

    def _train_step_impl(self, params, opt_state, rtg, obs, acts, tgt,
                         mask):
        def loss_fn(p):
            logits = self.model.apply(p, rtg, obs, acts)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, tgt[..., None],
                                       axis=-1)[..., 0]
            return (nll * mask).sum() / mask.sum()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def step(self) -> Dict:
        self._iter += 1
        loss = np.nan
        for _ in range(self.cfg["num_sgd_steps"]):
            rtg, obs, acts, tgt, mask = self._sample_batch()
            self.params, self.opt_state, jloss = self._train_step(
                self.params, self.opt_state, rtg, obs, acts, tgt, mask)
            loss = float(jloss)
        rets = [self.evaluate_episode(self.cfg["target_return"])
                for _ in range(self.cfg["num_eval_episodes"])]
        return {"episode_reward_mean": float(np.mean(rets)),
                "action_nll": loss,
                "training_iteration_": self._iter}

    # -------------------------------------------------------- evaluation
    def evaluate_episode(self, target_return: float) -> float:
        import gymnasium as gym
        cfg = self.cfg
        K = cfg["context_len"]
        env = gym.make(cfg["env"], **cfg["env_config"])
        obs, _ = env.reset(seed=int(self._rng.randint(2**31)))
        rtgs, obss, acts = [float(target_return)], [obs], []
        total = 0.0
        for _ in range(cfg["max_episode_steps"]):
            L = min(len(obss), K)
            rtg_in = np.zeros((1, K, 1), np.float32)
            obs_in = np.zeros((1, K, self.obs_dim), np.float32)
            act_in = np.full((1, K), self.num_actions, np.int64)
            rtg_in[0, :L, 0] = rtgs[-L:]
            obs_in[0, :L] = np.asarray(obss[-L:], np.float32)
            if len(acts) > 0:
                prev = acts[-(L - 1):] if L > 1 else []
                act_in[0, :len(prev)] = prev
            logits = self._forward(self.params, jnp.asarray(rtg_in),
                                   jnp.asarray(obs_in),
                                   jnp.asarray(act_in))
            a = int(np.asarray(logits)[0, L - 1].argmax())
            obs, reward, term, trunc, _ = env.step(a)
            total += float(reward)
            acts.append(a)
            obss.append(obs)
            rtgs.append(rtgs[-1] - float(reward))
            if term or trunc:
                break
        env.close()
        return total

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params),
                "iter": self._iter}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self._iter = data.get("iter", 0)
