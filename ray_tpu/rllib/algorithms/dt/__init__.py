from ray_tpu.rllib.algorithms.dt.dt import DT, DTConfig  # noqa: F401
