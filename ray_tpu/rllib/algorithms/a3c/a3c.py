"""A3C: asynchronous advantage actor-critic.

Reference: rllib/algorithms/a3c/a3c.py — each rollout worker computes
GRADIENTS on its own fragment; the learner applies them the moment they
arrive (no barrier) and ships fresh weights back to just that worker.
A2C (a2c.py) is the synchronous form sharing the same loss.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.rllib.algorithms.a2c.a2c import A2CPolicy
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class A3CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(A3C)
        self._config.update({
            "lr": 1e-3,
            "entropy_coeff": 0.01,
            "vf_loss_coeff": 0.5,
            "grads_per_step": 8,  # async grad applications per train()
        })


class A3C(Algorithm):
    policy_cls = A2CPolicy

    def _extra_defaults(self) -> Dict:
        return dict(A3CConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        # In-flight gradient computations persist ACROSS training_step
        # calls: no end-of-step drain, no discarded worker compute.
        self._inflight: Dict = {}

    def training_step(self) -> Dict:
        cfg = self.algo_config
        policy = self.workers.local_worker.policy
        workers = self.workers.remote_workers
        frag = cfg["rollout_fragment_length"]
        stats: Dict = {}
        trained = 0
        if not workers:
            # Degenerate single-process form: one sample+grad per call.
            for _ in range(cfg["grads_per_step"]):
                batch = self.workers.local_worker.sample(frag)
                grads, stats = policy.compute_grads(batch)
                policy.apply_grads(grads)
                trained += batch.count
            self._timesteps_total += trained
            return {"info": {"learner": stats},
                    "num_env_steps_trained": trained}
        # Keep one in-flight gradient computation per worker; apply each
        # as it lands and immediately refresh THAT worker's weights and
        # relaunch it — no synchronization barrier across workers, and
        # in-flight work carries over to the next training_step.
        busy = set(self._inflight.values())
        idle = [w for w in workers if w not in busy]
        if idle:
            wref = ray_tpu.put(self.workers.local_worker.get_weights())
            for w in idle:
                # Fire-and-forget broadcast: the sample get() behind it
                # observes actor failure.
                w.set_weights.remote(wref)  # noqa: RTL002
                self._inflight[w.sample_with_grads.remote(frag)] = w
        applied = 0
        while applied < cfg["grads_per_step"]:
            done, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                   timeout=300)
            if not done:
                break
            ref = done[0]
            w = self._inflight.pop(ref)
            grads, count, stats = ray_tpu.get(ref, timeout=60)
            policy.apply_grads(grads)
            applied += 1
            trained += count
            w.set_weights.remote(  # noqa: RTL002 (next sample observes)
                ray_tpu.put(self.workers.local_worker.get_weights()))
            self._inflight[w.sample_with_grads.remote(frag)] = w
        self._timesteps_total += trained
        return {"info": {"learner": stats},
                "num_env_steps_trained": trained}
