"""DQN: off-policy Q-learning with a replay buffer and target network.

Reference: rllib/algorithms/dqn/dqn.py training_step — sample rollouts
into the replay buffer, SGD on uniform replay batches, periodic target
sync, epsilon annealed on the workers.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_q_policy import JaxQPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DQN)
        self._config.update({
            "lr": 1e-3,
            "buffer_capacity": 50_000,
            "learning_starts": 1000,
            "train_batch_size": 1000,   # env steps collected per iter
            "sgd_batch_size": 64,
            "num_sgd_steps": 50,
            "target_update_freq": 4,    # iterations between target syncs
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_anneal_iters": 15,
        })


class DQN(Algorithm):
    policy_cls = JaxQPolicy

    def _extra_defaults(self) -> Dict:
        return dict(DQNConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        self.buffer = ReplayBuffer(self.algo_config["buffer_capacity"],
                                   seed=self.algo_config["seed"])
        self._iter = 0

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._iter / max(cfg["epsilon_anneal_iters"], 1))
        return (cfg["initial_epsilon"]
                + frac * (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    def training_step(self) -> Dict:
        cfg = self.algo_config
        self._iter += 1
        eps = self._epsilon()
        # Collect with the current epsilon on every worker.
        per_worker = max(1, cfg["train_batch_size"]
                         // max(1, len(self.workers.remote_workers)))
        if self.workers.remote_workers:
            weights = self.workers.local_worker.policy.get_weights()
            weights["epsilon"] = eps
            wref = ray_tpu.put(weights)
            ray_tpu.get([w.set_weights.remote(wref)
                         for w in self.workers.remote_workers],
                        timeout=300)
            batches = ray_tpu.get(
                self.workers.sample_all(per_worker), timeout=600)
        else:
            self.workers.local_worker.policy.epsilon = eps
            batches = [self.workers.local_worker.sample(per_worker)]
        batch = SampleBatch.concat_samples(batches)
        self.buffer.add(batch)
        self._timesteps_total += batch.count

        policy = self.workers.local_worker.policy
        stats: Dict = {}
        if len(self.buffer) >= cfg["learning_starts"]:
            for _ in range(cfg["num_sgd_steps"]):
                stats = policy.learn_on_batch(
                    self.buffer.sample(cfg["sgd_batch_size"]))
            if self._iter % cfg["target_update_freq"] == 0:
                policy.update_target()
        return {"info": {"learner": stats,
                         "buffer_size": len(self.buffer),
                         "epsilon": eps},
                "num_env_steps_trained": batch.count}

    def save_checkpoint(self) -> Dict:
        # Exploration schedule must survive restore (epsilon derives from
        # _iter); the replay buffer is deliberately not persisted — it
        # refills within a few iterations.
        data = super().save_checkpoint()
        data["dqn_iter"] = self._iter
        return data

    def load_checkpoint(self, data) -> None:
        super().load_checkpoint(data)
        if data:
            self._iter = data.get("dqn_iter", 0)
