"""DQN: off-policy Q-learning with a replay buffer and target network.

Reference: rllib/algorithms/dqn/dqn.py training_step — sample rollouts
into the replay buffer, SGD on uniform replay batches, periodic target
sync, epsilon annealed on the workers.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_q_policy import JaxQPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DQN)
        self._config.update({
            "lr": 1e-3,
            "buffer_capacity": 50_000,
            "learning_starts": 1000,
            "train_batch_size": 1000,   # env steps collected per iter
            "sgd_batch_size": 64,
            "num_sgd_steps": 50,
            "target_update_freq": 4,    # iterations between target syncs
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_anneal_iters": 15,
            # Prioritized replay (reference: dqn.py default
            # replay_buffer_config prioritized_replay_alpha/beta).
            "prioritized_replay": False,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
            # Iterations to anneal beta -> 1.0 (its own schedule — NOT
            # tied to the epsilon schedule).
            "prioritized_replay_beta_anneal_iters": 20,
        })


class DQN(Algorithm):
    policy_cls = JaxQPolicy

    def _extra_defaults(self) -> Dict:
        return dict(DQNConfig()._config)

    supports_policy_server = True

    def setup(self, config: Dict):
        super().setup(config)
        cfg = self.algo_config
        from ray_tpu.rllib.utils.replay_buffers import make_buffer
        self.buffer = make_buffer(cfg)
        self._iter = 0

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._iter / max(cfg["epsilon_anneal_iters"], 1))
        return (cfg["initial_epsilon"]
                + frac * (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    def training_step(self) -> Dict:
        cfg = self.algo_config
        self._iter += 1
        eps = self._epsilon()
        if self.policy_server is not None:
            # External-env serving: experience arrives from clients over
            # HTTP; block for at least one completed episode, then take
            # whatever else already landed.
            self.workers.local_worker.policy.epsilon = eps
            batches = []
            first = self.policy_server.next(timeout=60.0)
            if first is not None:
                batches = [first] + self.policy_server.try_drain()
            if not batches:
                return {"info": {"learner": {},
                                 "buffer_size": len(self.buffer),
                                 "epsilon": eps},
                        "num_env_steps_trained": 0}
            batch = SampleBatch.concat_samples(batches)
            return self._learn_from(batch, eps)
        # Collect with the current epsilon on every worker.
        per_worker = max(1, cfg["train_batch_size"]
                         // max(1, len(self.workers.remote_workers)))
        if self.workers.remote_workers:
            weights = self.workers.local_worker.policy.get_weights()
            weights["epsilon"] = eps
            wref = ray_tpu.put(weights)
            ray_tpu.get([w.set_weights.remote(wref)
                         for w in self.workers.remote_workers],
                        timeout=300)
            batches = ray_tpu.get(
                self.workers.sample_all(per_worker), timeout=600)
        else:
            self.workers.local_worker.policy.epsilon = eps
            batches = [self.workers.local_worker.sample(per_worker)]
        batch = SampleBatch.concat_samples(batches)
        return self._learn_from(batch, eps)

    def _learn_from(self, batch: SampleBatch, eps: float) -> Dict:
        cfg = self.algo_config
        self.buffer.add(batch)
        self._timesteps_total += batch.count

        policy = self.workers.local_worker.policy
        stats: Dict = {}
        prioritized = cfg.get("prioritized_replay")
        if prioritized:
            # Anneal beta -> 1 (full IS correction at convergence),
            # reference: prioritized replay beta schedule in dqn.py.
            frac = min(1.0, self._iter
                       / max(cfg.get(
                           "prioritized_replay_beta_anneal_iters", 20),
                           1))
            self.buffer.beta = (cfg["prioritized_replay_beta"]
                                + frac
                                * (1.0 - cfg["prioritized_replay_beta"]))
        if len(self.buffer) >= cfg["learning_starts"]:
            for _ in range(cfg["num_sgd_steps"]):
                replay = self.buffer.sample(cfg["sgd_batch_size"])
                stats = policy.learn_on_batch(replay)
                if prioritized:
                    # Feed the learner's fresh TD errors back as
                    # priorities (reference: dqn training_step
                    # update_priorities after train).
                    self.buffer.update_priorities(
                        replay["batch_indexes"], policy.last_td_errors)
            if self._iter % cfg["target_update_freq"] == 0:
                policy.update_target()
        return {"info": {"learner": stats,
                         "buffer_size": len(self.buffer),
                         "epsilon": eps},
                "num_env_steps_trained": batch.count}


    def save_checkpoint(self) -> Dict:
        # Exploration schedule must survive restore (epsilon derives from
        # _iter); the replay buffer is deliberately not persisted — it
        # refills within a few iterations.
        data = super().save_checkpoint()
        data["dqn_iter"] = self._iter
        return data

    def load_checkpoint(self, data) -> None:
        super().load_checkpoint(data)
        if data:
            self._iter = data.get("dqn_iter", 0)
