from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig  # noqa: F401
