from ray_tpu.rllib.algorithms.r2d2.r2d2 import R2D2, R2D2Config  # noqa: F401
