"""R2D2: recurrent replay distributed DQN (Kapturowski et al. 2019).

Reference: rllib/algorithms/r2d2/r2d2.py — DQN with an LSTM Q-network
trained on replayed SEQUENCES: each sampled segment is split into a
burn-in prefix (unrolled only to warm the recurrent state) and a
training suffix on which the double-Q TD loss is applied.  This is the
memory-equipped member of the DQN family — it solves partially
observable tasks feedforward DQN cannot.

Re-derived jax-first: the LSTM unroll is `nn.scan` inside the network,
so burn-in + train unroll + masked TD loss + adam compile into one
jitted step over a (B, T) segment batch; episode collection keeps the
carry across steps exactly as the deployed policy would.
"""

from __future__ import annotations

from typing import Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class _RecurrentQNet(nn.Module):
    """Dense -> LSTM (scanned over time) -> dueling Q head."""

    num_actions: int
    hidden: int = 64

    @nn.compact
    def __call__(self, obs_seq, carry):
        # obs_seq: (B, T, obs_dim); carry: LSTM (c, h) each (B, hidden).
        x = nn.relu(nn.Dense(self.hidden)(obs_seq))
        lstm = nn.scan(nn.OptimizedLSTMCell,
                       variable_broadcast="params",
                       split_rngs={"params": False},
                       in_axes=1, out_axes=1)(features=self.hidden)
        carry, h = lstm(carry, x)
        adv = nn.Dense(self.num_actions)(h)
        val = nn.Dense(1)(h)
        q = val + adv - adv.mean(axis=-1, keepdims=True)
        return q, carry

    @staticmethod
    def initial_carry(batch: int, hidden: int):
        zeros = jnp.zeros((batch, hidden), jnp.float32)
        return (zeros, zeros)


class R2D2Config:
    def __init__(self):
        self.algo_class = R2D2
        self._config: Dict = {
            "env": "CartPole-v1",
            "env_config": {},
            "lr": 1e-3,
            "gamma": 0.997,
            "lstm_hidden": 64,
            "burn_in": 8,
            "train_len": 20,
            "episodes_per_iter": 8,
            "max_episode_steps": 500,
            "buffer_capacity_episodes": 300,
            "train_batch_size": 32,      # segments per SGD step
            "num_sgd_steps": 40,
            "learning_starts_episodes": 16,
            "target_update_freq": 4,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_anneal_iters": 15,
            "double_q": True,
            "obs_mask": None,    # indices of obs dims VISIBLE to the
                                 # policy (None = all) — partial-obs knob
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "R2D2Config":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "R2D2Config":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "R2D2Config":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "R2D2":
        return R2D2(config=self.to_dict())


class R2D2(Trainable):
    def setup(self, config: Dict):
        defaults = R2D2Config().to_dict()
        defaults.update(config)
        self.cfg = defaults
        import gymnasium as gym
        self.env = gym.make(self.cfg["env"], **self.cfg["env_config"])
        full_dim = int(np.prod(self.env.observation_space.shape))
        self._mask = (np.asarray(self.cfg["obs_mask"], np.int64)
                      if self.cfg["obs_mask"] is not None else None)
        self.obs_dim = (len(self._mask) if self._mask is not None
                        else full_dim)
        self.num_actions = int(self.env.action_space.n)
        self.hidden = self.cfg["lstm_hidden"]
        self.net = _RecurrentQNet(num_actions=self.num_actions,
                                  hidden=self.hidden)
        rng = jax.random.PRNGKey(self.cfg["seed"])
        self.params = self.net.init(
            rng, jnp.zeros((1, 1, self.obs_dim), jnp.float32),
            _RecurrentQNet.initial_carry(1, self.hidden))
        self.target_params = self.params
        self.tx = optax.adam(self.cfg["lr"])
        self.opt_state = self.tx.init(self.params)
        self._forward = jax.jit(self.net.apply)
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._episodes: List[Dict] = []
        self._iter = 0
        self._timesteps_total = 0
        self._episode_rewards: List[float] = []

    def _see(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32).reshape(-1)
        return obs[self._mask] if self._mask is not None else obs

    def _epsilon(self) -> float:
        cfg = self.cfg
        frac = min(1.0, self._iter / max(cfg["epsilon_anneal_iters"], 1))
        return (cfg["initial_epsilon"]
                + frac * (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    # ---------------------------------------------------------- sampling
    def _run_episode(self, eps: float) -> float:
        obs, _ = self.env.reset(seed=int(self._rng.randint(2**31)))
        obs = self._see(obs)
        carry = _RecurrentQNet.initial_carry(1, self.hidden)
        rows = {"obs": [], "actions": [], "rewards": [], "dones": []}
        total = 0.0
        for _ in range(self.cfg["max_episode_steps"]):
            q, carry = self._forward(
                self.params, jnp.asarray(obs, jnp.float32)[None, None],
                carry)
            if self._rng.rand() < eps:
                a = int(self._rng.randint(self.num_actions))
            else:
                a = int(np.asarray(q)[0, 0].argmax())
            obs2, reward, term, trunc, _ = self.env.step(a)
            rows["obs"].append(obs)
            rows["actions"].append(a)
            rows["rewards"].append(float(reward))
            rows["dones"].append(bool(term))
            total += float(reward)
            self._timesteps_total += 1
            obs = self._see(obs2)
            if term or trunc:
                break
        rows["obs"].append(obs)  # trailing obs for the last TD target
        ep = {k: np.asarray(v) for k, v in rows.items()}
        ep["obs"] = ep["obs"].astype(np.float32)
        self._episodes.append(ep)
        if len(self._episodes) > self.cfg["buffer_capacity_episodes"]:
            self._episodes.pop(0)
        return total

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, params, target_params, opt_state, batch):
        cfg = self.cfg
        gamma = cfg["gamma"]
        burn = cfg["burn_in"]
        B = batch["obs"].shape[0]

        def loss_fn(p):
            carry0 = _RecurrentQNet.initial_carry(B, self.hidden)
            # Burn-in: warm the recurrent state without gradients.
            if burn > 0:
                _, carry = self.net.apply(
                    p, batch["obs"][:, :burn], carry0)
                carry = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                               carry)
                _, tcarry = self.net.apply(
                    target_params, batch["obs"][:, :burn], carry0)
            else:
                carry = tcarry = carry0
            # Train suffix; obs includes one trailing step for targets.
            seq = batch["obs"][:, burn:]
            q_all, _ = self.net.apply(p, seq, carry)
            tq_all, _ = self.net.apply(target_params, seq, tcarry)
            q = q_all[:, :-1]                       # (B, T, A)
            qa = jnp.take_along_axis(
                q, batch["actions"][..., None], axis=-1)[..., 0]
            if cfg["double_q"]:
                next_a = q_all[:, 1:].argmax(axis=-1)
                q_next = jnp.take_along_axis(
                    tq_all[:, 1:], next_a[..., None], axis=-1)[..., 0]
            else:
                q_next = tq_all[:, 1:].max(axis=-1)
            target = batch["rewards"] + gamma * q_next * (
                1.0 - batch["dones"].astype(jnp.float32))
            td = qa - jax.lax.stop_gradient(target)
            loss = (optax.huber_loss(td) * batch["mask"]).sum() \
                / jnp.maximum(batch["mask"].sum(), 1.0)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def _sample_segments(self):
        cfg = self.cfg
        B = cfg["train_batch_size"]
        burn, T = cfg["burn_in"], cfg["train_len"]
        span = burn + T
        obs = np.zeros((B, span + 1, self.obs_dim), np.float32)
        acts = np.zeros((B, T), np.int32)
        rews = np.zeros((B, T), np.float32)
        dones = np.zeros((B, T), np.bool_)
        mask = np.zeros((B, T), np.float32)
        for b in range(B):
            ep = self._episodes[self._rng.randint(len(self._episodes))]
            L = len(ep["actions"])
            start = self._rng.randint(0, max(1, L - burn))
            seg = min(span, L - start)
            obs[b, :seg + 1] = ep["obs"][start:start + seg + 1]
            train_lo = start + burn
            n = max(0, min(T, L - train_lo))
            if n > 0:
                acts[b, :n] = ep["actions"][train_lo:train_lo + n]
                rews[b, :n] = ep["rewards"][train_lo:train_lo + n]
                dones[b, :n] = ep["dones"][train_lo:train_lo + n]
                mask[b, :n] = 1.0
        return {k: jnp.asarray(v) for k, v in
                (("obs", obs), ("actions", acts), ("rewards", rews),
                 ("dones", dones), ("mask", mask))}

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        eps = self._epsilon()
        rets = [self._run_episode(eps)
                for _ in range(cfg["episodes_per_iter"])]
        self._episode_rewards += rets
        loss = np.nan
        if len(self._episodes) >= cfg["learning_starts_episodes"]:
            for _ in range(cfg["num_sgd_steps"]):
                batch = self._sample_segments()
                self.params, self.opt_state, jloss = self._train_step(
                    self.params, self.target_params, self.opt_state,
                    batch)
                loss = float(jloss)
            if self._iter % cfg["target_update_freq"] == 0:
                self.target_params = self.params
        recent = self._episode_rewards[-50:]
        return {"episode_reward_mean": float(np.mean(recent)),
                "episode_reward_this_iter": float(np.mean(rets)),
                "td_loss": loss, "epsilon": eps,
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params),
                "iter": self._iter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self.target_params = self.params
            self._iter = data.get("iter", 0)
            self._timesteps_total = data.get("timesteps_total", 0)

    def cleanup(self):
        try:
            self.env.close()
        except Exception:
            pass
