"""DD-PPO: decentralized PPO — no central learner, gradients allreduced
across the rollout workers themselves.

Reference: rllib/algorithms/ddppo/ddppo.py:91,131 (workers train locally
and allreduce via torch.distributed).  Here each worker's SGD minibatch
gradients ride the framework collective (ring allreduce for large
models), and replicas stay bit-identical because every worker applies
the same reduced gradients from identical initial weights.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.util import collective


class DDPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(DDPPO)
        self._config.update({
            "num_rollout_workers": 2,
            "lr": 1e-3,
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.0,
            "num_sgd_iter": 10,
            "sgd_minibatch_size": 128,
            "steps_per_worker": 1000,
        })


class DDPPO(Algorithm):
    def _extra_defaults(self) -> Dict:
        return {"lr": 1e-3, "clip_param": 0.2, "vf_loss_coeff": 0.5,
                "entropy_coeff": 0.0, "num_sgd_iter": 10,
                "sgd_minibatch_size": 128, "steps_per_worker": 1000}

    def setup(self, config: Dict):
        super().setup(config)
        workers = self.workers.remote_workers
        if len(workers) < 2:
            raise ValueError("DD-PPO needs num_rollout_workers >= 2")
        self._group = f"ddppo::{id(self):x}"
        collective.create_collective_group(
            workers, len(workers), list(range(len(workers))),
            group_name=self._group)
        # Identical starting point on every replica (decentralized sync
        # correctness depends on it).
        self.workers.sync_weights()

    def training_step(self) -> Dict:
        cfg = self.algo_config
        refs = [w.ddppo_epoch.remote(
            cfg["steps_per_worker"], cfg["num_sgd_iter"],
            cfg["sgd_minibatch_size"], self._group)
            for w in self.workers.remote_workers]
        outs = ray_tpu.get(refs, timeout=1800)
        steps = sum(o["steps"] for o in outs)
        self._timesteps_total += steps
        # Keep the local (checkpointing/eval) policy in lockstep.
        self.workers.local_worker.set_weights(ray_tpu.get(
            self.workers.remote_workers[0].get_weights.remote(),
            timeout=300))
        return {"info": {"learner": outs[0]["stats"]},
                "num_env_steps_trained": steps}

    def cleanup(self):
        try:
            collective.destroy_collective_group(self._group)
        except Exception:
            pass
        super().cleanup()
