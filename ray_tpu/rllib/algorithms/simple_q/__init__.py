from ray_tpu.rllib.algorithms.simple_q.simple_q import (  # noqa: F401
    SimpleQ,
    SimpleQConfig,
)
