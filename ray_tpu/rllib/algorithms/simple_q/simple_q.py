"""SimpleQ: vanilla DQN — the minimal Q-learning reference point.

Reference: rllib/algorithms/simple_q/simple_q.py — plain TD(0) targets
from a target network: no double-Q, no dueling, no prioritization.
Shares the replay/epsilon machinery with DQN (dqn.py); only the target
computation differs (policy double_q=False).
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = SimpleQ
        self._config.update({
            "double_q": False,
            "prioritized_replay": False,
        })


class SimpleQ(DQN):
    def _extra_defaults(self) -> Dict:
        d = dict(SimpleQConfig()._config)
        return d
