"""MB-MPO: model-based meta-policy optimization (Clavera et al. 2018).

Reference: rllib/algorithms/mbmpo/mbmpo.py — learn an ENSEMBLE of
dynamics models from real transitions, then treat each model as one
"task" in a MAML meta-objective: the meta-policy is trained so one
inner policy-gradient step inside any single model adapts it to that
model, making the policy robust to model error while training almost
entirely on imagined (model) rollouts.  Real-env interaction happens
only to (re)fit the models.

Re-designed jax-first on top of our MAML (algorithms/maml): the inner
adaptation + outer surrogate reuse MAML's exact grad-through-grad; the
ensemble members are bootstrap-trained MLP delta-dynamics models whose
one jitted train step fits all K models in parallel via vmap.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.maml.maml import (MAML, MAMLConfig,
                                                PointGoalEnv)


class _DynamicsNet(nn.Module):
    obs_dim: int
    hiddens: tuple = (128, 128)

    @nn.compact
    def __call__(self, obs, act):
        h = jnp.concatenate([obs, act], axis=-1)
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return nn.Dense(self.obs_dim)(h)  # predicts delta s


class MBMPOConfig(MAMLConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MBMPO
        self._config.update({
            "env_config": {},           # ONE real env (fixed task)
            "ensemble_size": 5,
            "model_hiddens": (128, 128),
            "model_lr": 1e-3,
            "model_train_steps": 200,
            "model_batch_size": 256,
            "real_episodes_per_iter": 8,
            "buffer_capacity": 20_000,
            # reward_fn(next_obs: np.ndarray) -> float for IMAGINED
            # states (the reference assumes a known/replayable reward);
            # None = derive from the env's `goal` attribute (point-task
            # family), and FAIL LOUDLY for envs without one.
            "reward_fn": None,
            # meta_batch_size is overridden: tasks == ensemble members.
        })


class MBMPO(MAML):
    """Each train(): collect a little real data -> refit the ensemble ->
    one MAML meta-step where task k's rollouts are IMAGINED inside
    model k."""

    def setup(self, config: Dict):
        defaults = MBMPOConfig().to_dict()
        defaults.update(config)
        super().setup(defaults)
        cfg = self.cfg
        self.real_env = cfg["env"](dict(cfg.get("env_config") or {},
                                        horizon=cfg["horizon"]))
        obs0, _ = self.real_env.reset(seed=0)
        self._reset_obs = np.asarray(obs0, np.float32)
        self.model = _DynamicsNet(obs_dim=self.obs_dim,
                                  hiddens=tuple(cfg["model_hiddens"]))
        K = cfg["ensemble_size"]
        keys = jax.random.split(jax.random.PRNGKey(cfg["seed"] + 99), K)
        zo = jnp.zeros((1, self.obs_dim), jnp.float32)
        za = jnp.zeros((1, self.act_dim), jnp.float32)
        self.model_params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[self.model.init(k, zo, za) for k in keys])
        self.reward_fn = cfg.get("reward_fn")
        if self.reward_fn is None:
            goal = getattr(self.real_env, "goal", None)
            if goal is None:
                raise ValueError(
                    "MBMPO imagines rollouts in learned models and "
                    "needs the REWARD of imagined states: pass "
                    "config reward_fn(next_obs)->float (the env has "
                    "no .goal to derive the point-task default from)")
            g = np.asarray(goal, np.float32)
            self.reward_fn = lambda obs2: -float(
                np.linalg.norm(obs2 - g))
        self.model_tx = optax.adam(cfg["model_lr"])
        self.model_opt = self.model_tx.init(self.model_params)
        self._model_forward = jax.jit(self.model.apply)
        self._model_train = jax.jit(self._model_train_impl)
        self._buffer: List[Dict] = []

    # -------------------------------------------------------- real data
    def _collect_real(self) -> float:
        cfg = self.cfg
        total = 0.0
        for _ in range(cfg["real_episodes_per_iter"]):
            obs, _ = self.real_env.reset(
                seed=int(self._rng.randint(2**31)))
            for _ in range(cfg["horizon"]):
                a = self._sample_action(self.params, obs)
                obs2, r, term, trunc, _ = self.real_env.step(a)
                self._buffer.append({
                    "obs": np.asarray(obs, np.float32),
                    "act": np.asarray(a, np.float32),
                    "delta": np.asarray(obs2, np.float32)
                    - np.asarray(obs, np.float32),
                    "reward": float(r)})
                total += r
                obs = obs2
                if term or trunc:
                    break
            if len(self._buffer) > cfg["buffer_capacity"]:
                self._buffer = self._buffer[-cfg["buffer_capacity"]:]
        return total / cfg["real_episodes_per_iter"]

    # ----------------------------------------------------- model fitting
    def _model_train_impl(self, params, opt_state, obs, act, delta):
        # obs/act/delta: (K, B, dim) — bootstrap batch per member.
        def loss_fn(p):
            pred = jax.vmap(self.model.apply)(p, obs, act)
            return ((pred - delta) ** 2).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.model_tx.update(grads, opt_state,
                                                  params)
        return optax.apply_updates(params, updates), opt_state, loss

    def _fit_models(self) -> float:
        cfg = self.cfg
        K, B = cfg["ensemble_size"], cfg["model_batch_size"]
        n = len(self._buffer)
        # Stack the frozen buffer ONCE; each step fancy-indexes the
        # contiguous arrays instead of re-walking the list of dicts.
        all_obs = np.stack([t["obs"] for t in self._buffer])
        all_act = np.stack([t["act"] for t in self._buffer])
        all_delta = np.stack([t["delta"] for t in self._buffer])
        loss = np.nan
        for _ in range(cfg["model_train_steps"]):
            idx = self._rng.randint(0, n, (K, min(B, n)))  # bootstrap
            self.model_params, self.model_opt, jloss = \
                self._model_train(self.model_params, self.model_opt,
                                  jnp.asarray(all_obs[idx]),
                                  jnp.asarray(all_act[idx]),
                                  jnp.asarray(all_delta[idx]))
            loss = float(jloss)
        return loss

    # -------------------------------------------------- imagined rollout
    def _collect_imagined(self, params, member: int) -> Dict:
        """MAML-style batch rolled out inside ensemble member k, using
        the REAL env's reward function on imagined states (the
        reference assumes a known/replayable reward)."""
        cfg = self.cfg
        mp = jax.tree_util.tree_map(lambda x: x[member],
                                    self.model_params)
        rows = {"obs": [], "actions": [], "rtg": []}
        total = 0.0
        for _ in range(cfg["episodes_per_task"]):
            obs = self._reset_obs.copy()
            ep_obs, ep_act, ep_rew = [], [], []
            for _ in range(cfg["horizon"]):
                a = self._sample_action(params, obs)
                delta = np.asarray(self._model_forward(
                    mp, jnp.asarray(obs)[None], jnp.asarray(a)[None]))[0]
                obs2 = obs + delta
                r = self.reward_fn(obs2)
                ep_obs.append(obs)
                ep_act.append(a)
                ep_rew.append(r)
                total += r
                obs = obs2
            g = 0.0
            rtg = []
            for r in reversed(ep_rew):
                g = r + cfg["gamma"] * g
                rtg.append(g)
            rtg.reverse()
            rows["obs"] += ep_obs
            rows["actions"] += ep_act
            rows["rtg"] += rtg
        batch = {k: np.asarray(v, np.float32) for k, v in rows.items()}
        adv = batch["rtg"] - batch["rtg"].mean()
        batch["adv"] = adv / max(adv.std(), 1e-6)  # match MAML scaling
        batch["mean_reward"] = total / cfg["episodes_per_task"]
        return batch

    # ---------------------------------------------------------- training
    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        real_reward = self._collect_real()
        model_loss = self._fit_models()
        meta_grads = None
        post = []
        for k in range(cfg["ensemble_size"]):
            inner = self._collect_imagined(self.params, k)
            inner.pop("mean_reward")
            adapted = self._adapt(
                self.params,
                {kk: jnp.asarray(v) for kk, v in inner.items()})
            outer = self._collect_imagined(adapted, k)
            post.append(outer.pop("mean_reward"))
            _, g = self._meta_grad(
                self.params,
                {kk: jnp.asarray(v) for kk, v in inner.items()},
                {kk: jnp.asarray(v) for kk, v in outer.items()})
            meta_grads = g if meta_grads is None else \
                jax.tree_util.tree_map(jnp.add, meta_grads, g)
        meta_grads = jax.tree_util.tree_map(
            lambda x: x / cfg["ensemble_size"], meta_grads)
        updates, self.opt_state = self.tx.update(
            meta_grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        return {"episode_reward_mean": real_reward,
                "imagined_post_adaptation_reward": float(np.mean(post)),
                "model_loss": model_loss,
                "buffer_size": len(self._buffer),
                "training_iteration_": self._iter}
