"""AlphaZero: MCTS-guided policy iteration — two-player self-play on
board games, plus a single-player variant for reward-ranked envs.

Reference: rllib/algorithms/alpha_zero/alpha_zero.py (+ mcts.py) — a
policy/value network guides Monte-Carlo tree search over a *cloneable*
environment (get_state/set_state); self-play episodes record the MCTS
visit distribution as the policy target and the game outcome as the
value target.

Two modes, auto-selected from the env:
- **Two-player** (the reference's actual domain class): alternating-
  move zero-sum board games (examples/board.py ConnectFour).  Values
  live in [-1, 1] from the mover's perspective; the UCB rule negates
  the child Q (the child's value is the opponent's), backup flips sign
  each ply, priors are masked to legal moves, and the value target is
  the final game outcome from each mover's seat.  Evaluation plays
  held-out games against scripted random and 1-ply-tactic opponents.
- **Single-player**: gym classic-control envs; regresses the
  normalized discounted return and min-max normalizes Q inside the
  UCB rule (the MuZero trick for unbounded scores).

Re-derived jax-first: one jitted policy+value forward serves every
MCTS expansion, and the (cross-entropy + value MSE) training step is a
single jitted function.  Tree search itself is Python — it's branchy,
data-dependent control flow that belongs on the host, not in XLA.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class CloneableGymEnv:
    """gymnasium env + get_state/set_state (reference alpha_zero requires
    envs expose exactly this pair; here we implement it generically for
    classic-control envs whose full state is `unwrapped.state`)."""

    def __init__(self, env_name: str, env_config: Dict):
        import gymnasium as gym
        self.env = gym.make(env_name, **(env_config or {}))

    def reset(self, seed=None):
        return self.env.reset(seed=seed)

    def step(self, action):
        return self.env.step(action)

    def get_state(self):
        u = self.env.unwrapped
        elapsed = getattr(self.env, "_elapsed_steps", 0)
        return (np.array(u.state, np.float64),
                u.steps_beyond_terminated, elapsed)

    def set_state(self, state):
        u = self.env.unwrapped
        arr, beyond, elapsed = state
        u.state = np.array(arr, np.float64)
        u.steps_beyond_terminated = beyond
        if hasattr(self.env, "_elapsed_steps"):
            self.env._elapsed_steps = elapsed
        return np.array(arr, np.float32)

    @property
    def action_space(self):
        return self.env.action_space

    @property
    def observation_space(self):
        return self.env.observation_space

    def close(self):
        self.env.close()


class _PVNet(nn.Module):
    num_actions: int
    hiddens: tuple = (64, 64)
    # Two-player games bound the value in [-1, 1] (tanh); single-player
    # normalized returns live in [0, 1] (sigmoid).
    two_player: bool = False

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        logits = nn.Dense(self.num_actions)(h)
        raw = nn.Dense(1)(h)[..., 0]
        value = jnp.tanh(raw) if self.two_player else nn.sigmoid(raw)
        return logits, value


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children", "state",
                 "reward", "terminal", "winner")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, "_Node"] = {}
        self.state = None
        self.reward = 0.0
        self.terminal = False
        self.winner = 0

    def q(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


class AlphaZeroConfig:
    def __init__(self):
        self.algo_class = AlphaZero
        self._config: Dict = {
            "env": "CartPole-v1",
            "env_config": {},
            "lr": 1e-3,
            "gamma": 0.997,
            "num_simulations": 25,
            "c_puct": 1.5,
            "dirichlet_alpha": 0.3,
            "dirichlet_frac": 0.25,
            "temperature_steps": 15,   # sample ~ visits before this ply
            "episodes_per_iter": 4,
            "max_episode_steps": 200,
            "value_scale": 200.0,      # returns normalized by this
            "replay_capacity": 5000,
            "train_batch_size": 128,
            "num_sgd_steps": 30,
            "fcnet_hiddens": (64, 64),
            "eval_games": 12,          # two-player: per opponent per iter
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "AlphaZeroConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "AlphaZeroConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "AlphaZeroConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "AlphaZero":
        return AlphaZero(config=self.to_dict())


class AlphaZero(Trainable):
    def setup(self, config: Dict):
        defaults = AlphaZeroConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        self._game_ctor = self._resolve_board_game()
        self.two_player = self._game_ctor is not None
        if self.two_player:
            self.game = self._game_ctor()
            self._eval_game = self._game_ctor()
            self.env = None
            self.obs_dim = self.game.obs_dim
            self.num_actions = self.game.num_actions
        else:
            self.env = CloneableGymEnv(self.cfg["env"],
                                       self.cfg["env_config"])
            self.obs_dim = int(np.prod(self.env.observation_space.shape))
            self.num_actions = int(self.env.action_space.n)
        self.net = _PVNet(num_actions=self.num_actions,
                          hiddens=tuple(self.cfg["fcnet_hiddens"]),
                          two_player=self.two_player)
        rng = jax.random.PRNGKey(self.cfg["seed"])
        self.params = self.net.init(
            rng, jnp.zeros((1, self.obs_dim), jnp.float32))
        self.tx = optax.adam(self.cfg["lr"])
        self.opt_state = self.tx.init(self.params)
        self._forward = jax.jit(self.net.apply)
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._replay: List[Dict] = []
        self._iter = 0
        self._timesteps_total = 0
        self._episode_rewards: List[float] = []

    # Everything two-player self-play/search/eval touches on a game;
    # a candidate missing any of it takes the single-player gym path
    # instead of crashing mid-search.
    _BOARD_PROTOCOL = ("apply", "to_move", "legal_actions",
                       "canonical_obs", "reset", "get_state",
                       "set_state", "greedy_move", "random_move",
                       "num_actions", "obs_dim")

    def _resolve_board_game(self):
        """Returns a zero-arg constructor when cfg['env'] names an
        alternating-move board game (examples/board.py protocol, see
        _BOARD_PROTOCOL), else None — which selects the single-player
        gym path."""
        spec = self.cfg["env"]
        cfg = self.cfg["env_config"]
        import ray_tpu.rllib.examples.board as board

        def _conforms(obj):
            return all(hasattr(obj, a) for a in self._BOARD_PROTOCOL)

        if isinstance(spec, str):
            cls = getattr(board, spec, None)
            # Probe an instance: protocol attributes like to_move are
            # set in __init__/reset, not on the class.
            if isinstance(cls, type) and _conforms(cls(cfg)):
                return lambda: cls(cfg)
            return None
        if callable(spec):
            probe = spec(cfg)
            if _conforms(probe):
                return lambda: spec(cfg)
        return None

    # -------------------------------------------------------------- MCTS
    def _eval_net(self, obs: np.ndarray):
        logits, value = self._forward(
            self.params, jnp.asarray(obs, jnp.float32)[None])
        probs = np.asarray(jax.nn.softmax(logits))[0]
        return probs, float(np.asarray(value)[0])

    def _search(self, root_obs: np.ndarray, root_state) -> np.ndarray:
        cfg = self.cfg
        gamma = cfg["gamma"]
        root = _Node(prior=1.0)
        root.state = root_state
        probs, value = self._eval_net(root_obs)
        noise = self._rng.dirichlet(
            [cfg["dirichlet_alpha"]] * self.num_actions)
        probs = ((1 - cfg["dirichlet_frac"]) * probs
                 + cfg["dirichlet_frac"] * noise)
        for a in range(self.num_actions):
            root.children[a] = _Node(prior=float(probs[a]))
        root.visits = 1
        root.value_sum = value
        q_min, q_max = value, value

        for _ in range(cfg["num_simulations"]):
            node, path = root, [root]
            # --- selection down to a leaf.
            while node.children and not node.terminal:
                total_n = math.sqrt(sum(c.visits
                                        for c in node.children.values()))
                best, best_score = None, -np.inf
                for a, child in node.children.items():
                    if child.visits and q_max > q_min:
                        qn = (child.q() - q_min) / (q_max - q_min)
                    else:
                        qn = 0.0
                    score = qn + cfg["c_puct"] * child.prior \
                        * total_n / (1 + child.visits)
                    if score > best_score:
                        best, best_score = a, score
                parent = node
                node = parent.children[best]
                if node.state is None and not node.terminal:
                    # --- expansion: materialize by stepping a clone.
                    self.env.set_state(parent.state)
                    obs2, reward, term, trunc, _ = self.env.step(best)
                    node.state = self.env.get_state()
                    node.reward = float(reward)
                    node.terminal = bool(term or trunc)
                    if not node.terminal:
                        p2, v2 = self._eval_net(np.asarray(obs2,
                                                           np.float32))
                        for a in range(self.num_actions):
                            node.children[a] = _Node(prior=float(p2[a]))
                        leaf_value = v2
                    else:
                        leaf_value = 0.0
                    path.append(node)
                    break
                path.append(node)
            else:
                leaf_value = 0.0 if node.terminal else node.q()
            # --- backup: each node is credited the value of its own
            # future; the entering-edge reward is added when moving to
            # the parent.
            value = leaf_value
            for n in reversed(path):
                n.visits += 1
                n.value_sum += value
                q_min = min(q_min, n.q())
                q_max = max(q_max, n.q())
                value = n.reward / cfg["value_scale"] + gamma * value
        visits = np.array([root.children[a].visits
                           for a in range(self.num_actions)], np.float64)
        return visits / visits.sum()

    # ---------------------------------------------------------- sampling
    def _self_play_episode(self) -> float:
        cfg = self.cfg
        obs, _ = self.env.reset(seed=int(self._rng.randint(2**31)))
        obs = np.asarray(obs, np.float32)
        rows = []
        total = 0.0
        rewards = []
        for ply in range(cfg["max_episode_steps"]):
            state = self.env.get_state()
            pi = self._search(obs, state)
            if ply < cfg["temperature_steps"]:
                a = int(self._rng.choice(self.num_actions, p=pi))
            else:
                a = int(pi.argmax())
            rows.append({"obs": obs, "pi": pi.astype(np.float32)})
            # Simulations mutated the env through set_state — restore
            # the real trajectory's state before the actual step.
            self.env.set_state(state)
            obs2, reward, term, trunc, _ = self.env.step(a)
            rewards.append(float(reward))
            total += float(reward)
            self._timesteps_total += 1
            obs = np.asarray(obs2, np.float32)
            if term or trunc:
                break
        # Discounted return-to-go as the value target, normalized.
        g = 0.0
        for row, r in zip(reversed(rows), reversed(rewards)):
            g = r + cfg["gamma"] * g
            row["z"] = np.float32(
                np.clip(g / cfg["value_scale"], 0.0, 1.0))
        self._replay.extend(rows)
        if len(self._replay) > cfg["replay_capacity"]:
            self._replay = self._replay[-cfg["replay_capacity"]:]
        return total

    # ------------------------------------------- two-player MCTS
    def _masked_priors(self, obs: np.ndarray, legal: List[int]):
        """Net forward with illegal moves masked out of the softmax."""
        logits, value = self._forward(
            self.params, jnp.asarray(obs, jnp.float32)[None])
        logits = np.asarray(logits, np.float64)[0]
        mask = np.full(self.num_actions, -np.inf)
        mask[legal] = 0.0
        x = logits + mask
        x -= x.max()
        p = np.exp(x)
        p /= p.sum()
        return p, float(np.asarray(value)[0])

    def _search2(self, game, add_noise: bool = True) -> np.ndarray:
        """Two-player MCTS from `game`'s current position.  Values are
        from the mover-at-node's perspective in [-1, 1]; the UCB rule
        negates the child Q (the child's mover is the opponent) and
        backup flips sign each ply.  Restores `game` before returning."""
        cfg = self.cfg
        root = _Node(prior=1.0)
        root.state = game.get_state()
        legal = game.legal_actions()
        probs, value = self._masked_priors(game.canonical_obs(), legal)
        if add_noise:
            noise = self._rng.dirichlet(
                [cfg["dirichlet_alpha"]] * len(legal))
            for i, a in enumerate(legal):
                probs[a] = ((1 - cfg["dirichlet_frac"]) * probs[a]
                            + cfg["dirichlet_frac"] * noise[i])
        for a in legal:
            root.children[a] = _Node(prior=float(probs[a]))
        root.visits = 1
        root.value_sum = value

        for _ in range(cfg["num_simulations"]):
            node, path = root, [root]
            leaf_value = 0.0
            while True:
                if node.terminal:
                    # The mover at a decided terminal node is the loser.
                    leaf_value = 0.0 if node.winner == 0 else -1.0
                    break
                sq = math.sqrt(node.visits)
                best_a, best_score = None, -np.inf
                for a, ch in node.children.items():
                    qe = -ch.q() if ch.visits else 0.0
                    score = qe + cfg["c_puct"] * ch.prior * sq \
                        / (1 + ch.visits)
                    if score > best_score:
                        best_a, best_score = a, score
                child = node.children[best_a]
                if child.state is None:
                    # Materialize by stepping a clone off the parent.
                    game.set_state(node.state)
                    _, winner = game.apply(best_a)
                    child.state = game.get_state()
                    if game.winner is not None:
                        child.terminal = True
                        child.winner = winner
                        leaf_value = 0.0 if winner == 0 else -1.0
                    else:
                        legal2 = game.legal_actions()
                        p2, v2 = self._masked_priors(
                            game.canonical_obs(), legal2)
                        for a2 in legal2:
                            child.children[a2] = _Node(
                                prior=float(p2[a2]))
                        leaf_value = v2
                    path.append(child)
                    break
                node = child
                path.append(node)
            value = leaf_value
            for n in reversed(path):
                n.visits += 1
                n.value_sum += value
                value = -value
        game.set_state(root.state)
        visits = np.zeros(self.num_actions, np.float64)
        for a, ch in root.children.items():
            visits[a] = ch.visits
        return visits / visits.sum()

    def _self_play_game(self) -> int:
        """One self-play game; both seats share the net.  Rows record
        (canonical obs, visit dist, mover); z is filled with the final
        outcome from each mover's seat."""
        cfg = self.cfg
        g = self.game
        g.reset()
        rows = []
        winner = 0
        # Ply cap is a safety net only — board games terminate on
        # their own (full board / win); max_episode_steps needs no
        # game-specific geometry.
        for ply in range(self.cfg["max_episode_steps"]):
            pi = self._search2(g)
            if ply < cfg["temperature_steps"]:
                a = int(self._rng.choice(self.num_actions, p=pi))
            else:
                a = int(pi.argmax())
            rows.append({"obs": g.canonical_obs(),
                         "pi": pi.astype(np.float32),
                         "mover": g.to_move})
            term, winner = g.apply(a)
            self._timesteps_total += 1
            if term:
                break
        for row in rows:
            row["z"] = np.float32(winner * row["mover"])
            del row["mover"]
        self._replay.extend(rows)
        if len(self._replay) > cfg["replay_capacity"]:
            self._replay = self._replay[-cfg["replay_capacity"]:]
        return winner

    def _play_eval_game(self, opponent: str, az_first: bool) -> float:
        """One held-out game vs a scripted opponent; returns the
        outcome from AlphaZero's seat (+1 win / 0 draw / -1 loss).
        No exploration noise; moves are argmax visit counts."""
        g = self._eval_game
        g.reset()
        az_seat = 1 if az_first else -1
        while True:
            if g.to_move == az_seat:
                pi = self._search2(g, add_noise=False)
                legal = g.legal_actions()
                a = int(max(legal, key=lambda c: pi[c]))
            elif opponent == "greedy":
                a = g.greedy_move(self._rng)
            else:
                a = g.random_move(self._rng)
            term, winner = g.apply(a)
            if term:
                return float(winner * az_seat)

    def _step_two_player(self) -> Dict:
        cfg = self.cfg
        outcomes = [self._self_play_game()
                    for _ in range(cfg["episodes_per_iter"])]
        loss = np.nan
        for _ in range(cfg["num_sgd_steps"]):
            if len(self._replay) < cfg["train_batch_size"]:
                break
            idx = self._rng.randint(0, len(self._replay),
                                    cfg["train_batch_size"])
            obs = jnp.asarray(np.stack(
                [self._replay[i]["obs"] for i in idx]))
            pi = jnp.asarray(np.stack(
                [self._replay[i]["pi"] for i in idx]))
            z = jnp.asarray(np.asarray(
                [self._replay[i]["z"] for i in idx], np.float32))
            self.params, self.opt_state, jloss = self._train_step(
                self.params, self.opt_state, obs, pi, z)
            loss = float(jloss)
        n = cfg["eval_games"]
        vs_random = [self._play_eval_game("random", i % 2 == 0)
                     for i in range(n)]
        vs_greedy = [self._play_eval_game("greedy", i % 2 == 0)
                     for i in range(n)]
        win_r = float(np.mean([o > 0 for o in vs_random]))
        win_g = float(np.mean([o > 0 for o in vs_greedy]))
        self._episode_rewards += [float(np.mean(vs_random))]
        return {"episode_reward_mean": float(np.mean(vs_random)),
                "win_rate_vs_random": win_r,
                "win_rate_vs_greedy": win_g,
                "self_play_first_mover_wins": float(
                    np.mean([o == 1 for o in outcomes])),
                "az_loss": loss,
                "timesteps_total": self._timesteps_total}

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, params, opt_state, obs, pi, z):
        def loss_fn(p):
            logits, value = self.net.apply(p, obs)
            policy_loss = -(pi * jax.nn.log_softmax(logits)).sum(-1)
            value_loss = (value - z) ** 2
            return (policy_loss + value_loss).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        if self.two_player:
            return self._step_two_player()
        rets = [self._self_play_episode()
                for _ in range(cfg["episodes_per_iter"])]
        self._episode_rewards += rets
        loss = np.nan
        for _ in range(cfg["num_sgd_steps"]):
            if len(self._replay) < cfg["train_batch_size"]:
                break
            idx = self._rng.randint(0, len(self._replay),
                                    cfg["train_batch_size"])
            obs = jnp.asarray(np.stack(
                [self._replay[i]["obs"] for i in idx]))
            pi = jnp.asarray(np.stack(
                [self._replay[i]["pi"] for i in idx]))
            z = jnp.asarray(np.asarray(
                [self._replay[i]["z"] for i in idx], np.float32))
            self.params, self.opt_state, jloss = self._train_step(
                self.params, self.opt_state, obs, pi, z)
            loss = float(jloss)
        recent = self._episode_rewards[-20:]
        return {"episode_reward_mean": float(np.mean(recent)),
                "episode_reward_this_iter": float(np.mean(rets)),
                "az_loss": loss,
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params),
                "iter": self._iter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self._iter = data.get("iter", 0)
            self._timesteps_total = data.get("timesteps_total", 0)

    def cleanup(self):
        try:
            if self.env is not None:
                self.env.close()
        except Exception:
            pass
