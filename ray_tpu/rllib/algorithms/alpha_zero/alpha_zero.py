"""AlphaZero (single-player): MCTS-guided policy iteration.

Reference: rllib/algorithms/alpha_zero/alpha_zero.py (+ mcts.py) — a
policy/value network guides Monte-Carlo tree search over a *cloneable*
environment (get_state/set_state); self-play episodes record the MCTS
visit distribution as the policy target and the episode's discounted
return as the value target.  The reference's single-player variant
ranks rewards instead of win/loss; ours regresses the normalized return
directly and min-max normalizes Q inside the UCB rule (the MuZero trick
for unbounded scores).

Re-derived jax-first: one jitted policy+value forward serves every
MCTS expansion, and the (cross-entropy + value MSE) training step is a
single jitted function.  Tree search itself is Python — it's branchy,
data-dependent control flow that belongs on the host, not in XLA.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class CloneableGymEnv:
    """gymnasium env + get_state/set_state (reference alpha_zero requires
    envs expose exactly this pair; here we implement it generically for
    classic-control envs whose full state is `unwrapped.state`)."""

    def __init__(self, env_name: str, env_config: Dict):
        import gymnasium as gym
        self.env = gym.make(env_name, **(env_config or {}))

    def reset(self, seed=None):
        return self.env.reset(seed=seed)

    def step(self, action):
        return self.env.step(action)

    def get_state(self):
        u = self.env.unwrapped
        elapsed = getattr(self.env, "_elapsed_steps", 0)
        return (np.array(u.state, np.float64),
                u.steps_beyond_terminated, elapsed)

    def set_state(self, state):
        u = self.env.unwrapped
        arr, beyond, elapsed = state
        u.state = np.array(arr, np.float64)
        u.steps_beyond_terminated = beyond
        if hasattr(self.env, "_elapsed_steps"):
            self.env._elapsed_steps = elapsed
        return np.array(arr, np.float32)

    @property
    def action_space(self):
        return self.env.action_space

    @property
    def observation_space(self):
        return self.env.observation_space

    def close(self):
        self.env.close()


class _PVNet(nn.Module):
    num_actions: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        logits = nn.Dense(self.num_actions)(h)
        value = nn.sigmoid(nn.Dense(1)(h))[..., 0]  # normalized [0, 1]
        return logits, value


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children", "state",
                 "reward", "terminal")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, "_Node"] = {}
        self.state = None
        self.reward = 0.0
        self.terminal = False

    def q(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


class AlphaZeroConfig:
    def __init__(self):
        self.algo_class = AlphaZero
        self._config: Dict = {
            "env": "CartPole-v1",
            "env_config": {},
            "lr": 1e-3,
            "gamma": 0.997,
            "num_simulations": 25,
            "c_puct": 1.5,
            "dirichlet_alpha": 0.3,
            "dirichlet_frac": 0.25,
            "temperature_steps": 15,   # sample ~ visits before this ply
            "episodes_per_iter": 4,
            "max_episode_steps": 200,
            "value_scale": 200.0,      # returns normalized by this
            "replay_capacity": 5000,
            "train_batch_size": 128,
            "num_sgd_steps": 30,
            "fcnet_hiddens": (64, 64),
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "AlphaZeroConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "AlphaZeroConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "AlphaZeroConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "AlphaZero":
        return AlphaZero(config=self.to_dict())


class AlphaZero(Trainable):
    def setup(self, config: Dict):
        defaults = AlphaZeroConfig().to_dict()
        defaults.update(config)
        self.cfg = defaults
        self.env = CloneableGymEnv(self.cfg["env"],
                                   self.cfg["env_config"])
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.num_actions = int(self.env.action_space.n)
        self.net = _PVNet(num_actions=self.num_actions,
                          hiddens=tuple(self.cfg["fcnet_hiddens"]))
        rng = jax.random.PRNGKey(self.cfg["seed"])
        self.params = self.net.init(
            rng, jnp.zeros((1, self.obs_dim), jnp.float32))
        self.tx = optax.adam(self.cfg["lr"])
        self.opt_state = self.tx.init(self.params)
        self._forward = jax.jit(self.net.apply)
        self._train_step = jax.jit(self._train_step_impl)
        self._rng = np.random.RandomState(self.cfg["seed"] + 1)
        self._replay: List[Dict] = []
        self._iter = 0
        self._timesteps_total = 0
        self._episode_rewards: List[float] = []

    # -------------------------------------------------------------- MCTS
    def _eval_net(self, obs: np.ndarray):
        logits, value = self._forward(
            self.params, jnp.asarray(obs, jnp.float32)[None])
        probs = np.asarray(jax.nn.softmax(logits))[0]
        return probs, float(np.asarray(value)[0])

    def _search(self, root_obs: np.ndarray, root_state) -> np.ndarray:
        cfg = self.cfg
        gamma = cfg["gamma"]
        root = _Node(prior=1.0)
        root.state = root_state
        probs, value = self._eval_net(root_obs)
        noise = self._rng.dirichlet(
            [cfg["dirichlet_alpha"]] * self.num_actions)
        probs = ((1 - cfg["dirichlet_frac"]) * probs
                 + cfg["dirichlet_frac"] * noise)
        for a in range(self.num_actions):
            root.children[a] = _Node(prior=float(probs[a]))
        root.visits = 1
        root.value_sum = value
        q_min, q_max = value, value

        for _ in range(cfg["num_simulations"]):
            node, path = root, [root]
            # --- selection down to a leaf.
            while node.children and not node.terminal:
                total_n = math.sqrt(sum(c.visits
                                        for c in node.children.values()))
                best, best_score = None, -np.inf
                for a, child in node.children.items():
                    if child.visits and q_max > q_min:
                        qn = (child.q() - q_min) / (q_max - q_min)
                    else:
                        qn = 0.0
                    score = qn + cfg["c_puct"] * child.prior \
                        * total_n / (1 + child.visits)
                    if score > best_score:
                        best, best_score = a, score
                parent = node
                node = parent.children[best]
                if node.state is None and not node.terminal:
                    # --- expansion: materialize by stepping a clone.
                    self.env.set_state(parent.state)
                    obs2, reward, term, trunc, _ = self.env.step(best)
                    node.state = self.env.get_state()
                    node.reward = float(reward)
                    node.terminal = bool(term or trunc)
                    if not node.terminal:
                        p2, v2 = self._eval_net(np.asarray(obs2,
                                                           np.float32))
                        for a in range(self.num_actions):
                            node.children[a] = _Node(prior=float(p2[a]))
                        leaf_value = v2
                    else:
                        leaf_value = 0.0
                    path.append(node)
                    break
                path.append(node)
            else:
                leaf_value = 0.0 if node.terminal else node.q()
            # --- backup: each node is credited the value of its own
            # future; the entering-edge reward is added when moving to
            # the parent.
            value = leaf_value
            for n in reversed(path):
                n.visits += 1
                n.value_sum += value
                q_min = min(q_min, n.q())
                q_max = max(q_max, n.q())
                value = n.reward / cfg["value_scale"] + gamma * value
        visits = np.array([root.children[a].visits
                           for a in range(self.num_actions)], np.float64)
        return visits / visits.sum()

    # ---------------------------------------------------------- sampling
    def _self_play_episode(self) -> float:
        cfg = self.cfg
        obs, _ = self.env.reset(seed=int(self._rng.randint(2**31)))
        obs = np.asarray(obs, np.float32)
        rows = []
        total = 0.0
        rewards = []
        for ply in range(cfg["max_episode_steps"]):
            state = self.env.get_state()
            pi = self._search(obs, state)
            if ply < cfg["temperature_steps"]:
                a = int(self._rng.choice(self.num_actions, p=pi))
            else:
                a = int(pi.argmax())
            rows.append({"obs": obs, "pi": pi.astype(np.float32)})
            # Simulations mutated the env through set_state — restore
            # the real trajectory's state before the actual step.
            self.env.set_state(state)
            obs2, reward, term, trunc, _ = self.env.step(a)
            rewards.append(float(reward))
            total += float(reward)
            self._timesteps_total += 1
            obs = np.asarray(obs2, np.float32)
            if term or trunc:
                break
        # Discounted return-to-go as the value target, normalized.
        g = 0.0
        for row, r in zip(reversed(rows), reversed(rewards)):
            g = r + cfg["gamma"] * g
            row["z"] = np.float32(
                np.clip(g / cfg["value_scale"], 0.0, 1.0))
        self._replay.extend(rows)
        if len(self._replay) > cfg["replay_capacity"]:
            self._replay = self._replay[-cfg["replay_capacity"]:]
        return total

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, params, opt_state, obs, pi, z):
        def loss_fn(p):
            logits, value = self.net.apply(p, obs)
            policy_loss = -(pi * jax.nn.log_softmax(logits)).sum(-1)
            value_loss = (value - z) ** 2
            return (policy_loss + value_loss).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        rets = [self._self_play_episode()
                for _ in range(cfg["episodes_per_iter"])]
        self._episode_rewards += rets
        loss = np.nan
        for _ in range(cfg["num_sgd_steps"]):
            if len(self._replay) < cfg["train_batch_size"]:
                break
            idx = self._rng.randint(0, len(self._replay),
                                    cfg["train_batch_size"])
            obs = jnp.asarray(np.stack(
                [self._replay[i]["obs"] for i in idx]))
            pi = jnp.asarray(np.stack(
                [self._replay[i]["pi"] for i in idx]))
            z = jnp.asarray(np.asarray(
                [self._replay[i]["z"] for i in idx], np.float32))
            self.params, self.opt_state, jloss = self._train_step(
                self.params, self.opt_state, obs, pi, z)
            loss = float(jloss)
        recent = self._episode_rewards[-20:]
        return {"episode_reward_mean": float(np.mean(recent)),
                "episode_reward_this_iter": float(np.mean(rets)),
                "az_loss": loss,
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        return {"params": jax.tree_util.tree_map(np.asarray,
                                                 self.params),
                "iter": self._iter,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.params = jax.tree_util.tree_map(jnp.asarray,
                                                 data["params"])
            self._iter = data.get("iter", 0)
            self._timesteps_total = data.get("timesteps_total", 0)

    def cleanup(self):
        try:
            self.env.close()
        except Exception:
            pass
