from ray_tpu.rllib.algorithms.alpha_zero.alpha_zero import (  # noqa: F401
    AlphaZero,
    AlphaZeroConfig,
)
