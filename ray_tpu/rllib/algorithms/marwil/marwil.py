"""MARWIL + BC: offline RL from a fixed batch of experience.

Reference: rllib/algorithms/marwil/marwil.py (exponentially-weighted
imitation: policy loss -exp(beta * A) * logp with a value head fit to
monte-carlo returns; BC is the beta=0 special case,
rllib/algorithms/bc/bc.py).  Re-derived jax-first: the weighted
imitation step is one jitted value_and_grad; the offline batch lives in
the object store and minibatches slice it zero-copy.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import sample_batch as sb
from ray_tpu.rllib.policy.jax_policy import JaxPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class MARWILPolicy(JaxPolicy):
    def _loss(self, params, batch):
        cfg = self.config
        beta = cfg.get("beta", 1.0)
        logits, value = self.model.apply(params, batch[sb.OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch[sb.ACTIONS]]
        vt = batch[sb.VALUE_TARGETS]
        adv = vt - jax.lax.stop_gradient(value)
        # Batch-normalized advantage inside the exponential weight
        # (reference keeps a running moment estimate; per-batch std is
        # the jit-friendly equivalent at this scale).
        adv_n = adv / (jnp.std(adv) + 1e-6)
        weight = jnp.minimum(jnp.exp(beta * adv_n),
                             cfg.get("max_weight", 20.0))
        imitation = -(jax.lax.stop_gradient(weight) * logp).mean()
        vf_loss = ((value - vt) ** 2).mean()
        total = imitation + cfg.get("vf_loss_coeff", 1.0) * vf_loss
        return total, {"policy_loss": imitation, "vf_loss": vf_loss,
                       "mean_weight": weight.mean()}


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(MARWIL)
        self._config.update({
            "beta": 1.0,
            "vf_loss_coeff": 1.0,
            "max_weight": 20.0,
            "lr": 5e-4,
            "num_rollout_workers": 0,   # offline: no rollout gang
            "sgd_minibatch_size": 256,
            "num_sgd_iter": 20,
            "evaluation_steps": 500,    # env steps of eval per train()
            "input_data": None,         # dict: obs/actions/rewards/dones
        })

    def offline_data(self, input_data) -> "MARWILConfig":
        self._config["input_data"] = input_data
        return self


class BCConfig(MARWILConfig):
    """Behavior cloning: MARWIL with beta=0 (pure imitation, reference
    bc.py)."""

    def __init__(self):
        super().__init__()
        self.algo_class = BC
        self._config.update({"beta": 0.0, "vf_loss_coeff": 0.0})


class MARWIL(Algorithm):
    policy_cls = MARWILPolicy

    def _extra_defaults(self) -> Dict:
        return {"beta": 1.0, "vf_loss_coeff": 1.0, "max_weight": 20.0,
                "lr": 5e-4, "num_rollout_workers": 0,
                "sgd_minibatch_size": 256, "num_sgd_iter": 20,
                "evaluation_steps": 500, "input_data": None}

    def setup(self, config: Dict):
        super().setup(config)
        data = self.algo_config.get("input_data")
        if data is None:
            raise ValueError("MARWIL/BC needs config['input_data'] with "
                             "obs/actions/rewards/dones arrays, or a "
                             "path/glob of offline .json files")
        if isinstance(data, str):
            # Offline dataset files (reference: rllib/offline JsonReader
            # feeding BC/MARWIL via config.offline_data(input_=...)).
            from ray_tpu.rllib.offline import read_sample_batches
            batch = read_sample_batches(data)
        else:
            batch = SampleBatch({k: np.asarray(v) for k, v in data.items()})
        batch[sb.VALUE_TARGETS] = _mc_returns(
            batch[sb.REWARDS].astype(np.float32),
            batch[sb.DONES].astype(np.float32),
            self.algo_config["gamma"])
        self.offline_batch = batch
        self._rng = np.random.RandomState(self.algo_config["seed"])

    def training_step(self) -> Dict:
        cfg = self.algo_config
        policy = self.workers.local_worker.policy
        mb = min(cfg["sgd_minibatch_size"], self.offline_batch.count)
        stats: Dict = {}
        for _ in range(cfg["num_sgd_iter"]):
            shuffled = self.offline_batch.shuffle(self._rng)
            for minibatch in shuffled.minibatches(mb):
                stats = policy.learn_on_batch(minibatch)
        self._timesteps_total += cfg["num_sgd_iter"] \
            * self.offline_batch.count
        # Online evaluation of the cloned policy (reference: evaluation
        # workers; here the local worker doubles as the eval sampler).
        if cfg["evaluation_steps"]:
            self.workers.local_worker.sample(cfg["evaluation_steps"])
        return {"info": {"learner": stats},
                "num_env_steps_trained": 0,
                "num_offline_steps_trained": self.offline_batch.count}


class BC(MARWIL):
    policy_cls = MARWILPolicy

    def _extra_defaults(self) -> Dict:
        d = super()._extra_defaults()
        d.update({"beta": 0.0, "vf_loss_coeff": 0.0})
        return d


def _mc_returns(rewards: np.ndarray, dones: np.ndarray,
                gamma: float) -> np.ndarray:
    """Discounted monte-carlo returns, resetting at episode boundaries
    (reference: marwil postprocess_advantages with
    use_gae=False)."""
    out = np.zeros_like(rewards)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        acc = rewards[t] + gamma * acc * (1.0 - dones[t])
        out[t] = acc
    return out
