from ray_tpu.rllib.algorithms.marwil.marwil import (  # noqa: F401
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
)
