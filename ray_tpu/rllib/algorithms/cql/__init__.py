from ray_tpu.rllib.algorithms.cql.cql import CQL, CQLConfig  # noqa: F401
