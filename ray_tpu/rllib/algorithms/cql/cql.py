"""CQL: conservative Q-learning for OFFLINE continuous control.

Reference: rllib/algorithms/cql/cql.py — SAC trained purely from a
fixed dataset, with the CQL(H) regularizer pushing down Q on
out-of-distribution actions (logsumexp over sampled actions) while
holding it up on dataset actions, so the policy can't exploit
over-estimated unseen actions.  The penalty lives in the continuous SAC
policy's critic loss (policy/jax_sac_policy.py, cql_min_q_weight).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_sac_policy import SACPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(CQL)
        self._config.update({
            "lr": 3e-4,
            "tau": 0.995,
            "initial_alpha": 0.1,
            "cql_min_q_weight": 5.0,
            "cql_n_actions": 4,
            "num_rollout_workers": 0,  # offline: no rollout gang
            "sgd_batch_size": 256,
            "num_sgd_steps": 100,
            "input_data": None,  # dict obs/actions/rewards/dones/new_obs
            "evaluation_steps": 0,
        })

    def offline_data(self, input_data) -> "CQLConfig":
        self._config["input_data"] = input_data
        return self


class CQL(Algorithm):
    policy_cls = SACPolicy

    def _extra_defaults(self) -> Dict:
        return dict(CQLConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        if self.workers.local_worker._discrete:
            # The CQL(H) penalty lives in the CONTINUOUS SAC critic
            # loss; silently training plain discrete SAC would drop the
            # conservatism CQL exists for.
            raise TypeError("CQL requires a continuous (Box) action "
                            "space (reference cql.py trains on top of "
                            "continuous SAC)")
        data = self.algo_config.get("input_data")
        if data is None:
            raise ValueError("CQL needs config.offline_data(...) with "
                             "obs/actions/rewards/dones/new_obs arrays "
                             "or a path of offline .json files")
        if isinstance(data, str):
            from ray_tpu.rllib.offline import read_sample_batches
            self.offline_batch = read_sample_batches(data)
        else:
            self.offline_batch = SampleBatch(
                {k: np.asarray(v) for k, v in data.items()})
        self._rng = np.random.RandomState(self.algo_config["seed"])

    def training_step(self) -> Dict:
        cfg = self.algo_config
        policy = self.workers.local_worker.policy
        n = self.offline_batch.count
        stats: Dict = {}
        for _ in range(cfg["num_sgd_steps"]):
            idx = self._rng.randint(0, n, size=min(cfg["sgd_batch_size"],
                                                   n))
            mb = SampleBatch({k: v[idx]
                              for k, v in self.offline_batch.items()})
            stats = policy.learn_on_batch(mb)
            policy.update_target()
        # Optional online evaluation of the learned policy.
        if cfg["evaluation_steps"]:
            self.workers.local_worker.sample(cfg["evaluation_steps"])
        return {"info": {"learner": stats},
                "num_env_steps_trained": 0,
                "num_offline_steps_trained":
                    cfg["num_sgd_steps"] * min(cfg["sgd_batch_size"], n)}
