"""DDPG: deep deterministic policy gradient for continuous control.

Reference: rllib/algorithms/ddpg/ddpg.py — off-policy replay,
deterministic actor + Q critic with polyak targets, Gaussian (or OU)
exploration noise on the workers.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_ddpg_policy import JaxDDPGPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class DDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self._config.update({
            "actor_lr": 1e-3,
            "critic_lr": 1e-3,
            "tau": 0.995,
            "exploration_noise": 0.1,
            "buffer_capacity": 50_000,
            "learning_starts": 500,
            "train_batch_size": 500,  # env steps collected per iter
            "sgd_batch_size": 128,
            "num_sgd_steps": 64,
            # TD3 knobs, off in base DDPG (td3.py flips them).
            "twin_q": False,
            "policy_delay": 1,
            "target_noise": 0.0,
            "target_noise_clip": 0.5,
            "prioritized_replay": False,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
        })


class DDPG(Algorithm):
    policy_cls = JaxDDPGPolicy

    def _extra_defaults(self) -> Dict:
        return dict(DDPGConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        from ray_tpu.rllib.utils.replay_buffers import make_buffer
        self.buffer = make_buffer(self.algo_config)

    def training_step(self) -> Dict:
        cfg = self.algo_config
        per_worker = max(1, cfg["train_batch_size"]
                         // max(1, len(self.workers.remote_workers)))
        if self.workers.remote_workers:
            batches = ray_tpu.get(
                self.workers.sample_all(per_worker), timeout=600)
        else:
            batches = [self.workers.local_worker.sample(per_worker)]
        batch = SampleBatch.concat_samples(batches)
        self.buffer.add(batch)
        self._timesteps_total += batch.count

        policy = self.workers.local_worker.policy
        stats: Dict = {}
        if len(self.buffer) >= cfg["learning_starts"]:
            prioritized = cfg.get("prioritized_replay")
            for _ in range(cfg["num_sgd_steps"]):
                replay = self.buffer.sample(cfg["sgd_batch_size"])
                stats = policy.learn_on_batch(replay)
                if prioritized:
                    self.buffer.update_priorities(
                        replay["batch_indexes"], policy.last_td_errors)
        if self.workers.remote_workers:
            self.workers.sync_weights()
        return {"info": {"learner": stats,
                         "buffer_size": len(self.buffer)},
                "num_env_steps_trained": batch.count}
