from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPG, DDPGConfig  # noqa: F401
