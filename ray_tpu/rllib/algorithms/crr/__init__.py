from ray_tpu.rllib.algorithms.crr.crr import CRR, CRRConfig  # noqa: F401
