"""CRR: critic-regularized regression — offline continuous control by
advantage-weighted behavior cloning against a TD-learned twin critic.

Reference: rllib/algorithms/crr/crr.py — like CQL an offline algorithm
(no rollout gang), but instead of penalizing OOD Q values it filters the
behavior-cloning loss by the critic's advantage so only better-than-
average dataset actions are imitated.  Loss math in
policy/jax_crr_policy.py.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.jax_crr_policy import JaxCRRPolicy
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class CRRConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(CRR)
        self._config.update({
            "lr": 3e-4,
            "critic_lr": 3e-4,
            "tau": 0.995,
            "crr_weight_type": "bin",   # "bin" (1[A>0]) or "exp"
            "crr_beta": 1.0,            # exp-weight temperature
            "crr_n_action_samples": 4,
            "num_rollout_workers": 0,   # offline: no rollout gang
            "sgd_batch_size": 256,
            "num_sgd_steps": 100,
            "input_data": None,
            "evaluation_steps": 0,
        })

    def offline_data(self, input_data) -> "CRRConfig":
        self._config["input_data"] = input_data
        return self


class CRR(Algorithm):
    policy_cls = JaxCRRPolicy

    def _extra_defaults(self) -> Dict:
        return dict(CRRConfig()._config)

    def setup(self, config: Dict):
        super().setup(config)
        data = self.algo_config.get("input_data")
        if data is None:
            raise ValueError("CRR needs config.offline_data(...) with "
                             "obs/actions/rewards/dones/new_obs arrays "
                             "or a path of offline .json files")
        if isinstance(data, str):
            from ray_tpu.rllib.offline import read_sample_batches
            self.offline_batch = read_sample_batches(data)
        else:
            self.offline_batch = SampleBatch(
                {k: np.asarray(v) for k, v in data.items()})
        self._rng = np.random.RandomState(self.algo_config["seed"])

    def training_step(self) -> Dict:
        cfg = self.algo_config
        policy = self.workers.local_worker.policy
        n = self.offline_batch.count
        stats: Dict = {}
        for _ in range(cfg["num_sgd_steps"]):
            idx = self._rng.randint(0, n,
                                    size=min(cfg["sgd_batch_size"], n))
            mb = SampleBatch({k: v[idx]
                              for k, v in self.offline_batch.items()})
            stats = policy.learn_on_batch(mb)
        if cfg["evaluation_steps"]:
            self.workers.local_worker.sample(cfg["evaluation_steps"])
        return {"info": {"learner": stats},
                "num_env_steps_trained": 0,
                "num_offline_steps_trained":
                    cfg["num_sgd_steps"] * min(cfg["sgd_batch_size"], n)}
