from ray_tpu.rllib.algorithms.bandit.bandit import (  # noqa: F401
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
)
