"""Contextual bandits: LinUCB and linear Thompson sampling.

Reference: rllib/algorithms/bandit/bandit.py (BanditLinUCB/BanditLinTS
over rllib/algorithms/bandit/bandit_torch_model.py's
DiscreteLinearModel).  Closed-form ridge-regression posteriors per arm —
exact Sherman-Morrison updates, no SGD, so this is numpy, not a neural
policy.  Envs are one-step: obs = context, Discrete arms, reward per
pull (see SimpleContextualBandit in the tests, mirroring
rllib/env/bandit_envs_discrete.py).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class LinearBanditPolicy:
    """Per-arm ridge regression: A_a = I + sum x x^T, b_a = sum r x.
    UCB mode scores theta^T x + alpha sqrt(x^T A^-1 x); TS mode samples
    theta ~ N(theta_hat, nu^2 A^-1)."""

    def __init__(self, obs_dim: int, num_actions: int, config: Dict):
        self.config = config
        self.mode = config.get("bandit_mode", "ucb")
        self.alpha = float(config.get("ucb_alpha", 1.0))
        self.nu = float(config.get("ts_nu", 0.5))
        self.num_actions = num_actions
        self.obs_dim = obs_dim
        self._rng = np.random.RandomState(config.get("seed", 0))
        self.A_inv = np.stack([np.eye(obs_dim, dtype=np.float64)
                               for _ in range(num_actions)])
        self.b = np.zeros((num_actions, obs_dim), np.float64)

    # ---------------------------------------------------------- acting
    def compute_actions(self, obs: np.ndarray):
        obs = np.asarray(obs, np.float64)
        theta = np.einsum("aij,aj->ai", self.A_inv, self.b)
        actions = []
        for x in obs:
            if self.mode == "ts":
                scores = [
                    float(self._rng.multivariate_normal(
                        theta[a], self.nu ** 2 * self.A_inv[a]) @ x)
                    for a in range(self.num_actions)]
            else:
                scores = [
                    float(theta[a] @ x + self.alpha
                          * np.sqrt(x @ self.A_inv[a] @ x))
                    for a in range(self.num_actions)]
            actions.append(int(np.argmax(scores)))
        zeros = np.zeros(len(obs), np.float32)
        return np.asarray(actions, np.int64), zeros, zeros

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)

    # -------------------------------------------------------- learning
    def learn_on_batch(self, batch) -> Dict[str, float]:
        obs = np.asarray(batch["obs"], np.float64)
        acts = np.asarray(batch["actions"], np.int64)
        rews = np.asarray(batch["rewards"], np.float64)
        for x, a, r in zip(obs, acts, rews):
            # Sherman-Morrison rank-1 update of A_inv.
            Ax = self.A_inv[a] @ x
            self.A_inv[a] -= np.outer(Ax, Ax) / (1.0 + x @ Ax)
            self.b[a] += r * x
        theta = np.einsum("aij,aj->ai", self.A_inv, self.b)
        pred = np.einsum("ni,ni->n", theta[acts], obs)
        return {"total_loss": float(((pred - rews) ** 2).mean()),
                "mean_reward": float(rews.mean())}

    def update_target(self):
        pass

    # --------------------------------------------------------- weights
    def get_weights(self):
        return {"A_inv": self.A_inv.copy(), "b": self.b.copy()}

    def set_weights(self, weights):
        self.A_inv = np.asarray(weights["A_inv"], np.float64).copy()
        self.b = np.asarray(weights["b"], np.float64).copy()


class BanditLinUCBConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinUCB)
        self._config.update({
            "bandit_mode": "ucb",
            "ucb_alpha": 1.0,
            "ts_nu": 0.5,
            "num_rollout_workers": 0,
            "rollout_fragment_length": 100,
            "train_batch_size": 100,
        })


class BanditLinTSConfig(BanditLinUCBConfig):
    def __init__(self):
        super().__init__(BanditLinTS)
        self._config.update({"bandit_mode": "ts"})


class BanditLinUCB(Algorithm):
    policy_cls = LinearBanditPolicy

    def _extra_defaults(self) -> Dict:
        return dict(BanditLinUCBConfig()._config)

    def training_step(self) -> Dict:
        cfg = self.algo_config
        per_worker = max(1, cfg["train_batch_size"]
                         // max(1, len(self.workers.remote_workers)))
        if self.workers.remote_workers:
            batches = ray_tpu.get(
                self.workers.sample_all(per_worker), timeout=600)
        else:
            batches = [self.workers.local_worker.sample(per_worker)]
        batch = SampleBatch.concat_samples(batches)
        policy = self.workers.local_worker.policy
        stats = policy.learn_on_batch(batch)
        if self.workers.remote_workers:
            self.workers.sync_weights()
        self._timesteps_total += batch.count
        return {"info": {"learner": stats},
                "num_env_steps_trained": batch.count}


class BanditLinTS(BanditLinUCB):
    def _extra_defaults(self) -> Dict:
        return dict(BanditLinTSConfig()._config)
