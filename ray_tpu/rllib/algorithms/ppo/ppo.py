"""PPO: synchronous sample -> minibatch SGD epochs -> weight broadcast.

Reference: rllib/algorithms/ppo/ppo.py:288 (training_step :400) +
execution/rollout_ops.py:36 synchronous_parallel_sample and
execution/train_ops.py:42 train_one_step.  The learner lives in the local
worker; on TPU the jitted train step runs each minibatch on-chip.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(PPO)
        self._config.update({
            "lr": 1e-3,
            "clip_param": 0.2,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.0,
            "num_sgd_iter": 15,
            "sgd_minibatch_size": 128,
        })


class PPO(Algorithm):
    def _extra_defaults(self) -> Dict:
        return {"lr": 1e-3, "clip_param": 0.2, "vf_loss_coeff": 0.5,
                "entropy_coeff": 0.0, "num_sgd_iter": 15,
                "sgd_minibatch_size": 128}

    def training_step(self) -> Dict:
        cfg = self.algo_config
        # 1. Synchronous parallel sampling across the worker set
        # (reference: synchronous_parallel_sample rollout_ops.py:36).
        target = cfg["train_batch_size"]
        per_worker = max(1, target
                         // max(1, len(self.workers.remote_workers)))
        batches = []
        collected = 0
        while collected < target:
            refs = self.workers.sample_all(per_worker)
            if not refs:  # num_rollout_workers=0: sample locally
                b = self.workers.local_worker.sample(per_worker)
                batches.append(b)
                collected += b.count
                continue
            for b in ray_tpu.get(refs, timeout=600):
                batches.append(b)
                collected += b.count
        if self.is_multi_agent:
            return self._multi_agent_train(batches)
        train_batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += train_batch.count

        # Advantage normalization over the full batch (reference PPO
        # standardize_fields=["advantages"]).
        adv = train_batch["advantages"]
        train_batch["advantages"] = (
            (adv - adv.mean()) / max(adv.std(), 1e-6)).astype(np.float32)

        # 2. SGD epochs over shuffled minibatches (train_ops.py:42).
        policy = self.workers.local_worker.policy
        rng = np.random.RandomState(cfg["seed"])
        stats: Dict = {}
        mb = min(cfg["sgd_minibatch_size"], train_batch.count)
        for _ in range(cfg["num_sgd_iter"]):
            shuffled = train_batch.shuffle(rng)
            for minibatch in shuffled.minibatches(mb):
                stats = policy.learn_on_batch(minibatch)

        # 3. Broadcast fresh weights to the rollout workers.
        self.workers.sync_weights()
        return {"info": {"learner": stats},
                "num_env_steps_trained": train_batch.count}

    def _multi_agent_train(self, batches) -> Dict:
        """Per-policy SGD over a MultiAgentBatch (reference: multi-agent
        train_one_step — each policy trains only on the experience its
        agents generated)."""
        from ray_tpu.rllib.evaluation.multi_agent_worker import (
            MultiAgentBatch)
        cfg = self.algo_config
        ma = MultiAgentBatch.concat_samples(batches)
        self._timesteps_total += ma.count
        rng = np.random.RandomState(cfg["seed"])
        policies = self.workers.local_worker.policies
        stats: Dict = {}
        for pid, batch in ma.items():
            if pid not in policies or batch.count == 0:
                continue
            adv = batch["advantages"]
            batch["advantages"] = (
                (adv - adv.mean()) / max(adv.std(), 1e-6)
            ).astype(np.float32)
            policy = policies[pid]
            mb = min(cfg["sgd_minibatch_size"], batch.count)
            for _ in range(cfg["num_sgd_iter"]):
                shuffled = batch.shuffle(rng)
                for minibatch in shuffled.minibatches(mb):
                    stats[pid] = policy.learn_on_batch(minibatch)
        self.workers.sync_weights()
        return {"info": {"learner": stats},
                "num_env_steps_trained": ma.count}
