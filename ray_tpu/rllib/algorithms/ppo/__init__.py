from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig  # noqa: F401
