from ray_tpu.rllib.algorithms.dreamer.dreamer import Dreamer, DreamerConfig  # noqa: F401
