"""Dreamer: learning behaviors by latent imagination (Hafner et al.
2020).

Reference: rllib/algorithms/dreamer/dreamer.py — an RSSM world model
(deterministic GRU path + stochastic latent, with encoder, decoder,
and reward head) is trained on replayed real sequences; the actor and
value critic are then trained ENTIRELY inside the model by
backpropagating lambda-returns through imagined latent rollouts.

Re-designed jax-first: the world-model update and the imagination
update are each ONE jitted function — reparameterized latents make the
actor gradient flow through the learned dynamics exactly (no
likelihood-ratio estimator), which is the heart of the algorithm.
Observations select the encoder/decoder pair: 3-D (pixel) obs get the
reference's conv stack (_ConvEncoder/_ConvDecoder, cf.
dreamer_model.py:23,71 — e.g. examples/pixel.py PixelPendulum, where
velocity must be integrated across frames by the RSSM), flat obs get
an MLP pair.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.tune.trainable import Trainable


class _RSSM(nn.Module):
    """h_t = GRU(h_{t-1}, [z_{t-1}, a_{t-1}]);  prior p(z_t|h_t);
    posterior q(z_t|h_t, embed_t)."""

    stoch: int = 16
    deter: int = 64
    hidden: int = 64

    def setup(self):
        self.gru = nn.GRUCell(features=self.deter)
        self.inp = nn.Dense(self.hidden)
        self.prior_net = nn.Sequential(
            [nn.Dense(self.hidden), nn.elu, nn.Dense(2 * self.stoch)])
        self.post_net = nn.Sequential(
            [nn.Dense(self.hidden), nn.elu, nn.Dense(2 * self.stoch)])

    def _stats(self, net, x):
        mean, std = jnp.split(net(x), 2, axis=-1)
        return mean, nn.softplus(std) + 0.1

    def step(self, h, z, a):
        x = nn.elu(self.inp(jnp.concatenate([z, a], -1)))
        h, _ = self.gru(h, x)
        return h

    def prior(self, h):
        return self._stats(self.prior_net, h)

    def posterior(self, h, embed):
        return self._stats(self.post_net,
                           jnp.concatenate([h, embed], -1))

    def __call__(self, h, z, a, embed):
        # Single init-path call so .init sees every submodule.
        h = self.step(h, z, a)
        return self.prior(h), self.posterior(h, embed)


class _MLP(nn.Module):
    out: int
    hiddens: tuple = (64, 64)
    final_tanh: bool = False

    @nn.compact
    def __call__(self, x):
        for width in self.hiddens:
            x = nn.elu(nn.Dense(width)(x))
        x = nn.Dense(self.out)(x)
        return jnp.tanh(x) if self.final_tanh else x


class _ConvEncoder(nn.Module):
    """Pixel encoder (reference: dreamer_model.py:23 ConvEncoder, a
    strided-conv stack).  Takes FLATTENED frames — the RSSM plumbing
    is shape-agnostic that way — and reshapes internally."""

    out: int
    shape: tuple  # (H, W, C)

    @nn.compact
    def __call__(self, x):
        img = x.reshape((x.shape[0],) + self.shape)
        h = nn.relu(nn.Conv(16, (4, 4), strides=2)(img))
        h = nn.relu(nn.Conv(32, (4, 4), strides=2)(h))
        h = nn.relu(nn.Conv(32, (3, 3), strides=2)(h))
        return nn.Dense(self.out)(h.reshape(x.shape[0], -1))


class _ConvDecoder(nn.Module):
    """Latent-to-frame transposed-conv stack (reference:
    dreamer_model.py:71 ConvDecoder); emits flattened frames so the
    reconstruction loss is identical to the proprio path."""

    shape: tuple  # (H, W, C) with H == W and H divisible by 8

    @nn.compact
    def __call__(self, feat):
        n = feat.shape[0]
        s = self.shape[0] // 8
        h = nn.Dense(s * s * 32)(feat).reshape(n, s, s, 32)
        h = nn.relu(nn.ConvTranspose(32, (3, 3), strides=(2, 2))(h))
        h = nn.relu(nn.ConvTranspose(16, (4, 4), strides=(2, 2))(h))
        h = nn.ConvTranspose(self.shape[-1], (4, 4),
                             strides=(2, 2))(h)
        return h.reshape(n, -1)


class DreamerConfig:
    def __init__(self):
        self.algo_class = Dreamer
        self._config: Dict = {
            "env": "Pendulum-v1",
            "env_config": {},
            "stoch": 16, "deter": 64, "hidden": 64,
            "model_lr": 3e-4, "actor_lr": 1e-4, "critic_lr": 1e-4,
            "gamma": 0.99, "lambda": 0.95,
            "imagine_horizon": 15,
            "seq_len": 20,
            "batch_size": 32,
            "model_train_steps": 40,
            "behavior_train_steps": 40,
            "episodes_per_iter": 4,
            "max_episode_steps": 100,
            # Each policy action is held for this many env steps with
            # rewards summed (the reference Dreamer's action-repeat
            # wrapper; standard for pixel control — halves the horizon
            # the world model must carry).
            "action_repeat": 1,
            # Rewards are scaled by this inside the world model and
            # imagination (metrics stay unscaled).  Dreamer's value
            # learning assumes roughly unit-scale rewards (the DMC
            # suite's [0, 1] per step); gym Pendulum's [-16, 0] breaks
            # that — set ~1/16 there.
            "reward_scale": 1.0,
            "expl_noise": 0.3,
            "expl_noise_decay": 0.9,
            "buffer_capacity_episodes": 200,
            "free_nats": 1.0,
            "kl_scale": 1.0,
            "seed": 0,
        }

    def environment(self, env=None, env_config=None) -> "DreamerConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def training(self, **kwargs) -> "DreamerConfig":
        self._config.update(kwargs)
        return self

    def debugging(self, seed=None) -> "DreamerConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "Dreamer":
        return Dreamer(config=self.to_dict())


class Dreamer(Trainable):
    def setup(self, config: Dict):
        defaults = DreamerConfig().to_dict()
        defaults.update(config)
        self.cfg = cfg = defaults
        import gymnasium as gym
        env = cfg["env"]
        if isinstance(env, str):
            import ray_tpu.rllib.examples.pixel as _pixel_envs
            cls = getattr(_pixel_envs, env, None)
            self.env = (cls(cfg["env_config"]) if cls is not None
                        else gym.make(env, **cfg["env_config"]))
        else:
            self.env = env(cfg["env_config"])
        obs_shape = self.env.observation_space.shape
        # 3-D observations select the conv encoder/decoder pair — the
        # reference Dreamer's pixel domain (dreamer_model.py:23,71).
        self.pixel_obs = len(obs_shape) == 3
        self.obs_dim = int(np.prod(obs_shape))
        space = self.env.action_space
        self.act_dim = int(np.prod(space.shape))
        self._act_low = np.asarray(space.low, np.float32).reshape(-1)
        self._act_high = np.asarray(space.high, np.float32).reshape(-1)
        self._scale = (self._act_high - self._act_low) / 2.0
        self._center = (self._act_high + self._act_low) / 2.0

        S, D, H = cfg["stoch"], cfg["deter"], cfg["hidden"]
        self.rssm = _RSSM(stoch=S, deter=D, hidden=H)
        if self.pixel_obs:
            if obs_shape[0] != obs_shape[1] or obs_shape[0] % 8:
                raise ValueError(
                    f"pixel Dreamer needs square frames with side "
                    f"divisible by 8 (the decoder upsamples 2x three "
                    f"times from side/8); got {obs_shape}")
            self.encoder = _ConvEncoder(out=H, shape=obs_shape)
            self.decoder = _ConvDecoder(shape=obs_shape)
        else:
            self.encoder = _MLP(out=H)
            self.decoder = _MLP(out=self.obs_dim)
        self.reward_head = _MLP(out=1)
        self.actor = _MLP(out=self.act_dim, final_tanh=True)
        self.critic = _MLP(out=1)

        k = jax.random.split(jax.random.PRNGKey(cfg["seed"]), 6)
        zh = jnp.zeros((1, D)); zz = jnp.zeros((1, S))
        za = jnp.zeros((1, self.act_dim)); ze = jnp.zeros((1, H))
        zf = jnp.zeros((1, D + S)); zo = jnp.zeros((1, self.obs_dim))
        self.wm_params = {
            "rssm": self.rssm.init(k[0], zh, zz, za, ze),
            "enc": self.encoder.init(k[1], zo),
            "dec": self.decoder.init(k[2], zf),
            "rew": self.reward_head.init(k[3], zf),
        }
        self.actor_params = self.actor.init(k[4], zf)
        self.critic_params = self.critic.init(k[5], zf)
        self.wm_tx = optax.adam(cfg["model_lr"])
        self.actor_tx = optax.adam(cfg["actor_lr"])
        self.critic_tx = optax.adam(cfg["critic_lr"])
        self.wm_opt = self.wm_tx.init(self.wm_params)
        self.actor_opt = self.actor_tx.init(self.actor_params)
        self.critic_opt = self.critic_tx.init(self.critic_params)
        self._key = jax.random.PRNGKey(cfg["seed"] + 1)
        self._rng = np.random.RandomState(cfg["seed"] + 2)
        self._episodes: List[Dict] = []
        self._episode_rewards: List[float] = []
        self._iter = 0
        self._timesteps_total = 0
        self._wm_train = jax.jit(self._wm_train_impl)
        self._behavior_train = jax.jit(self._behavior_train_impl)
        self._policy_step = jax.jit(self._policy_step_impl)
        self._observe_jit = jax.jit(self._observe_seq)

    # ------------------------------------------------------- acting
    def _policy_step_impl(self, wm, actor_params, h, z, a_prev, obs, key):
        embed = nn.elu(self.encoder.apply(wm["enc"], obs))
        h = self.rssm.apply(wm["rssm"], h, z, a_prev,
                            method=_RSSM.step)
        mean, std = self.rssm.apply(wm["rssm"], h, embed,
                                    method=_RSSM.posterior)
        z = mean + std * jax.random.normal(key, mean.shape)
        feat = jnp.concatenate([h, z], -1)
        act = self.actor.apply(actor_params, feat)
        return h, z, act

    def _run_episode(self, noise: float) -> float:
        cfg = self.cfg
        obs, _ = self.env.reset(seed=int(self._rng.randint(2**31)))
        obs = np.asarray(obs, np.float32).reshape(-1)
        h = jnp.zeros((1, cfg["deter"]))
        z = jnp.zeros((1, cfg["stoch"]))
        a_prev = jnp.zeros((1, self.act_dim))
        rows = {"obs": [], "actions": [], "rewards": []}
        total = 0.0
        for _ in range(cfg["max_episode_steps"]):
            self._key, k = jax.random.split(self._key)
            h, z, act = self._policy_step(self.wm_params,
                                          self.actor_params, h, z,
                                          a_prev, jnp.asarray(obs)[None],
                                          k)
            a = np.asarray(act)[0]
            a = np.clip(a + noise * self._rng.randn(self.act_dim),
                        -1.0, 1.0).astype(np.float32)
            env_a = (a * self._scale + self._center).reshape(
                self.env.action_space.shape)
            r = 0.0
            term = trunc = False
            for _ in range(cfg["action_repeat"]):
                obs2, r1, term, trunc, _ = self.env.step(env_a)
                r += float(r1)
                self._timesteps_total += 1
                if term or trunc:
                    break
            rows["obs"].append(obs)
            rows["actions"].append(a)
            rows["rewards"].append(float(r))
            total += float(r)
            obs = np.asarray(obs2, np.float32).reshape(-1)
            a_prev = jnp.asarray(a)[None]
            if term or trunc:
                break
        self._episodes.append(
            {k2: np.asarray(v, np.float32) for k2, v in rows.items()})
        if len(self._episodes) > cfg["buffer_capacity_episodes"]:
            self._episodes.pop(0)
        return total

    # ------------------------------------------------- world model
    def _observe_seq(self, wm, obs_seq, act_seq, key):
        """Roll the posterior through a (B, L, ...) sequence; returns
        stacked feats + KL terms."""
        B, L = obs_seq.shape[0], obs_seq.shape[1]
        embed = nn.elu(self.encoder.apply(
            wm["enc"], obs_seq.reshape(B * L, -1))).reshape(B, L, -1)

        def step(carry, t):
            h, z, k = carry
            a_prev = jnp.where(t > 0, act_seq[:, t - 1], 0.0)
            h = self.rssm.apply(wm["rssm"], h, z, a_prev,
                                method=_RSSM.step)
            pm, ps = self.rssm.apply(wm["rssm"], h, method=_RSSM.prior)
            qm, qs = self.rssm.apply(wm["rssm"], h, embed[:, t],
                                     method=_RSSM.posterior)
            k, sub = jax.random.split(k)
            z = qm + qs * jax.random.normal(sub, qm.shape)
            kl = (jnp.log(ps / qs)
                  + (qs ** 2 + (qm - pm) ** 2) / (2 * ps ** 2)
                  - 0.5).sum(-1)
            return (h, z, k), (jnp.concatenate([h, z], -1), kl)

        h0 = jnp.zeros((B, self.cfg["deter"]))
        z0 = jnp.zeros((B, self.cfg["stoch"]))
        (_, _, _), (feats, kls) = jax.lax.scan(
            step, (h0, z0, key), jnp.arange(L))
        # scan stacked on axis 0 = time; -> (B, L, ...)
        return feats.swapaxes(0, 1), kls.swapaxes(0, 1)

    def _wm_train_impl(self, wm, opt_state, obs_seq, act_seq, rew_seq,
                       mask_seq, key):
        cfg = self.cfg

        def loss_fn(p):
            feats, kls = self._observe_seq(p, obs_seq, act_seq, key)
            B, L = obs_seq.shape[0], obs_seq.shape[1]
            flat = feats.reshape(B * L, -1)
            recon = self.decoder.apply(p["dec"], flat).reshape(
                B, L, -1)
            rew = self.reward_head.apply(p["rew"], flat).reshape(B, L)
            # Mask zero-padded tails of short episodes: the model must
            # not fit fabricated post-termination transitions.
            denom = jnp.maximum(mask_seq.sum(), 1.0)
            recon_loss = (((recon - obs_seq) ** 2).sum(-1)
                          * mask_seq).sum() / denom
            rew_loss = (((rew - rew_seq) ** 2) * mask_seq).sum() / denom
            kl_loss = jnp.maximum(
                (kls * mask_seq).sum() / denom, cfg["free_nats"])
            return (recon_loss + rew_loss
                    + cfg["kl_scale"] * kl_loss), (recon_loss, rew_loss,
                                                   kl_loss)

        (loss, aux), grads = jax.value_and_grad(loss_fn,
                                                has_aux=True)(wm)
        updates, opt_state = self.wm_tx.update(grads, opt_state, wm)
        return optax.apply_updates(wm, updates), opt_state, loss, aux

    # -------------------------------------------------- imagination
    def _imagine(self, wm, actor_params, h, z, key):
        cfg = self.cfg

        def step(carry, _):
            h, z, k = carry
            feat = jnp.concatenate([h, z], -1)
            a = self.actor.apply(actor_params, feat)
            h = self.rssm.apply(wm["rssm"], h, z, a, method=_RSSM.step)
            pm, ps = self.rssm.apply(wm["rssm"], h, method=_RSSM.prior)
            k, sub = jax.random.split(k)
            z = pm + ps * jax.random.normal(sub, pm.shape)
            return (h, z, k), jnp.concatenate([h, z], -1)

        (_, _, _), feats = jax.lax.scan(step, (h, z, key), None,
                                        length=cfg["imagine_horizon"])
        return feats  # (H, N, feat)

    def _behavior_train_impl(self, wm, actor_params, critic_params,
                             actor_opt, critic_opt, start_feats, key):
        cfg = self.cfg
        gamma, lam = cfg["gamma"], cfg["lambda"]
        D = cfg["deter"]
        h0 = start_feats[:, :D]
        z0 = start_feats[:, D:]

        def actor_loss_fn(ap):
            feats = self._imagine(wm, ap, h0, z0, key)
            rew = self.reward_head.apply(
                wm["rew"], feats.reshape(-1, feats.shape[-1])
            ).reshape(feats.shape[0], feats.shape[1])
            val = self.critic.apply(
                critic_params, feats.reshape(-1, feats.shape[-1])
            ).reshape(feats.shape[0], feats.shape[1])
            # lambda-returns, backward over the imagined horizon.
            def lam_step(nxt, t):
                ret = rew[t] + gamma * ((1 - lam) * val[t] + lam * nxt)
                return ret, ret
            last = val[-1]
            _, rets = jax.lax.scan(
                lam_step, last,
                jnp.arange(feats.shape[0] - 1, -1, -1))
            returns = rets[::-1]
            return -returns.mean(), (feats, returns)

        (a_loss, (feats, returns)), a_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True)(actor_params)
        a_updates, actor_opt = self.actor_tx.update(a_grads, actor_opt,
                                                    actor_params)
        actor_params = optax.apply_updates(actor_params, a_updates)

        feats_sg = jax.lax.stop_gradient(feats)
        returns_sg = jax.lax.stop_gradient(returns)

        def critic_loss_fn(cp):
            val = self.critic.apply(
                cp, feats_sg.reshape(-1, feats_sg.shape[-1])
            ).reshape(feats_sg.shape[0], feats_sg.shape[1])
            return ((val - returns_sg) ** 2).mean()

        c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
            critic_params)
        c_updates, critic_opt = self.critic_tx.update(
            c_grads, critic_opt, critic_params)
        critic_params = optax.apply_updates(critic_params, c_updates)
        return (actor_params, critic_params, actor_opt, critic_opt,
                a_loss, c_loss)

    # ----------------------------------------------------- training
    def _sample_seq_batch(self):
        cfg = self.cfg
        B, L = cfg["batch_size"], cfg["seq_len"]
        obs = np.zeros((B, L, self.obs_dim), np.float32)
        act = np.zeros((B, L, self.act_dim), np.float32)
        rew = np.zeros((B, L), np.float32)
        mask = np.zeros((B, L), np.float32)
        for b in range(B):
            ep = self._episodes[self._rng.randint(len(self._episodes))]
            T = len(ep["rewards"])
            if T <= L:
                obs[b, :T] = ep["obs"][:T]
                act[b, :T] = ep["actions"][:T]
                rew[b, :T] = ep["rewards"][:T]
                mask[b, :T] = 1.0
            else:
                s = self._rng.randint(0, T - L)
                obs[b] = ep["obs"][s:s + L]
                act[b] = ep["actions"][s:s + L]
                rew[b] = ep["rewards"][s:s + L]
                mask[b] = 1.0
        rew *= cfg["reward_scale"]
        return (jnp.asarray(obs), jnp.asarray(act), jnp.asarray(rew),
                jnp.asarray(mask))

    def step(self) -> Dict:
        cfg = self.cfg
        self._iter += 1
        noise = max(0.05, cfg["expl_noise"]
                    * (cfg["expl_noise_decay"] ** self._iter))
        rets = [self._run_episode(noise)
                for _ in range(cfg["episodes_per_iter"])]
        self._episode_rewards += rets
        wm_loss = a_loss = c_loss = np.nan
        for _ in range(cfg["model_train_steps"]):
            obs, act, rew, mask = self._sample_seq_batch()
            self._key, k = jax.random.split(self._key)
            self.wm_params, self.wm_opt, jl, aux = self._wm_train(
                self.wm_params, self.wm_opt, obs, act, rew, mask, k)
            wm_loss = float(jl)
        for _ in range(cfg["behavior_train_steps"]):
            obs, act, rew, mask = self._sample_seq_batch()
            self._key, k1 = jax.random.split(self._key)
            self._key, k2 = jax.random.split(self._key)
            feats, _ = self._observe_jit(self.wm_params, obs, act, k1)
            start = jax.lax.stop_gradient(
                feats.reshape(-1, feats.shape[-1]))
            (self.actor_params, self.critic_params, self.actor_opt,
             self.critic_opt, ja, jc) = self._behavior_train(
                self.wm_params, self.actor_params, self.critic_params,
                self.actor_opt, self.critic_opt, start, k2)
            a_loss, c_loss = float(ja), float(jc)
        recent = self._episode_rewards[-20:]
        return {"episode_reward_mean": float(np.mean(recent)),
                "episode_reward_this_iter": float(np.mean(rets)),
                "world_model_loss": wm_loss,
                "actor_loss": a_loss, "critic_loss": c_loss,
                "exploration_noise": noise,
                "timesteps_total": self._timesteps_total}

    def save_checkpoint(self) -> Dict:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa
        return {"wm": to_np(self.wm_params),
                "actor": to_np(self.actor_params),
                "critic": to_np(self.critic_params),
                "iter": self._iter}

    def load_checkpoint(self, data) -> None:
        if data:
            to_j = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa
            self.wm_params = to_j(data["wm"])
            self.actor_params = to_j(data["actor"])
            self.critic_params = to_j(data["critic"])
            self._iter = data.get("iter", 0)

    def cleanup(self):
        try:
            self.env.close()
        except Exception:
            pass
