"""Algorithm: the RL training harness, a Tune Trainable.

Reference: rllib/algorithms/algorithm.py:145 — Algorithm subclasses
Trainable (so Tuner drives it), builds a WorkerSet in setup(), and each
train() call runs one `training_step` returning metrics including
episode_reward_mean.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib.evaluation.worker_set import WorkerSet
from ray_tpu.rllib.policy.jax_policy import JaxPolicy
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent config builder (reference: algorithm_config.py)."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self._config: Dict = {
            "env": None,
            "env_config": {},
            "num_rollout_workers": 2,
            "rollout_fragment_length": 200,
            "train_batch_size": 2000,
            "gamma": 0.99,
            "lambda": 0.95,
            "lr": 5e-4,
            "seed": 0,
            "fcnet_hiddens": (64, 64),
        }

    def environment(self, env=None, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        return self

    def rollouts(self, num_rollout_workers=None,
                 rollout_fragment_length=None, num_envs_per_worker=None,
                 output=None) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self._config["num_rollout_workers"] = num_rollout_workers
        if rollout_fragment_length is not None:
            self._config["rollout_fragment_length"] = \
                rollout_fragment_length
        if num_envs_per_worker is not None:
            self._config["num_envs_per_worker"] = num_envs_per_worker
        if output is not None:
            # Offline recording: every sampled fragment is appended as a
            # dataset row (reference: rollout config `output` ->
            # offline/json_writer).
            self._config["output"] = output
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self._config.update(kwargs)
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None
                    ) -> "AlgorithmConfig":
        """Configure multi-agent training (reference:
        algorithm_config.py multi_agent()).  `policies` maps policy_id ->
        PolicySpec (or an agent_id whose spaces size the policy);
        `policy_mapping_fn(agent_id) -> policy_id`."""
        if policies is not None:
            self._config["policies"] = policies
        if policy_mapping_fn is not None:
            self._config["policy_mapping_fn"] = policy_mapping_fn
        return self

    def serving(self, policy_server: bool = True,
                server_host: str = "127.0.0.1",
                server_port: int = 0) -> "AlgorithmConfig":
        """External-env serving (reference: policy_server_input.py):
        rollouts come from external clients over HTTP instead of local
        env sampling; the algorithm exposes `algo.policy_server`."""
        self._config["input"] = ("policy_server" if policy_server
                                 else "sampler")
        self._config["policy_server_host"] = server_host
        self._config["policy_server_port"] = server_port
        return self

    def evaluation(self, evaluation_interval=None,
                   evaluation_duration=None,
                   evaluation_config=None,
                   evaluation_max_steps=None) -> "AlgorithmConfig":
        """Periodic greedy evaluation (reference: algorithm_config.py
        evaluation() + Algorithm.evaluate): every
        `evaluation_interval` train() calls, run
        `evaluation_duration` episodes with exploration off and report
        under result["evaluation"]."""
        if evaluation_interval is not None:
            self._config["evaluation_interval"] = evaluation_interval
        if evaluation_duration is not None:
            self._config["evaluation_duration"] = evaluation_duration
        if evaluation_config is not None:
            self._config["evaluation_config"] = dict(evaluation_config)
        if evaluation_max_steps is not None:
            self._config["evaluation_max_steps"] = evaluation_max_steps
        return self

    def debugging(self, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self._config["seed"] = seed
        return self

    def to_dict(self) -> Dict:
        return dict(self._config)

    def build(self) -> "Algorithm":
        return self.algo_class(config=self.to_dict())


def _default_env_creator(config: Dict):
    from ray_tpu.rllib.env.registry import resolve_env_creator
    return resolve_env_creator(config["env"])(
        config.get("env_config", {}))


class Algorithm(Trainable):
    """Base: subclasses override training_step() (reference: algorithm.py
    step :629 -> training_step :1141)."""

    policy_cls = JaxPolicy

    def setup(self, config: Dict):
        defaults = AlgorithmConfig(type(self)).to_dict()
        defaults.update(self._extra_defaults())
        defaults.update(config)
        self.algo_config = defaults
        self.is_multi_agent = bool(self.algo_config.get("policies"))
        worker_cls = None
        if self.is_multi_agent:
            from ray_tpu.rllib.evaluation.multi_agent_worker import (
                MultiAgentRolloutWorker)
            worker_cls = MultiAgentRolloutWorker
            self.algo_config.setdefault(
                "policy_mapping_fn",
                lambda agent_id, *a, **kw: "default_policy")
        self.workers = WorkerSet(
            _default_env_creator, self.policy_cls, self.algo_config,
            num_workers=self.algo_config["num_rollout_workers"],
            worker_cls=worker_cls)
        self._timesteps_total = 0
        self._episode_rewards: list = []
        self.policy_server = None
        if self.algo_config.get("input") == "policy_server":
            if not getattr(self, "supports_policy_server", False):
                raise ValueError(
                    f"{type(self).__name__} does not consume external-"
                    "env serving input (.serving()); algorithms that do "
                    "declare supports_policy_server = True (e.g. DQN)")
            from ray_tpu.rllib.env.policy_server_input import (
                PolicyServerInput)
            self.policy_server = PolicyServerInput(
                lambda: self.workers.local_worker.policy,
                host=self.algo_config.get("policy_server_host",
                                          "127.0.0.1"),
                port=self.algo_config.get("policy_server_port", 0))

    def _extra_defaults(self) -> Dict:
        return {}

    def training_step(self) -> Dict:
        raise NotImplementedError

    def step(self) -> Dict:
        t0 = time.time()
        result = self.training_step()
        stats = self.workers.episode_stats()
        self._episode_rewards += stats["episode_rewards"]
        if self.policy_server is not None:
            # External-env episodes completed over HTTP count too.
            self._episode_rewards += \
                self.policy_server.drain_episode_rewards()
        recent = self._episode_rewards[-100:]
        result.setdefault("episode_reward_mean",
                          float(np.mean(recent)) if recent else np.nan)
        result["episodes_total"] = len(self._episode_rewards)
        result["timesteps_total"] = self._timesteps_total
        self._train_iters = getattr(self, "_train_iters", 0) + 1
        interval = self.algo_config.get("evaluation_interval")
        if interval and self._train_iters % interval == 0:
            if self.is_multi_agent:
                if not getattr(self, "_warned_ma_eval", False):
                    self._warned_ma_eval = True
                    import logging
                    logging.getLogger(__name__).warning(
                        "evaluation_interval is single-agent only; "
                        "skipping periodic evaluation for this "
                        "multi-agent algorithm")
            else:
                result.update(self.evaluate())
        result["time_this_iter_s"] = time.time() - t0
        return result

    # -------------------------------------------------------- evaluation
    def compute_single_action(self, obs, explore: bool = False):
        """One action for one observation (reference:
        Algorithm.compute_single_action).  explore=False uses the
        policy's deterministic_actions path (argmax for logits
        policies, noise-free actor for DDPG/TD3); policies without one
        fall back to their sampling compute_actions."""
        if self.is_multi_agent:
            raise NotImplementedError(
                "compute_single_action is single-agent; call the "
                "per-policy compute_actions via "
                "workers.local_worker.policies[policy_id]")
        pol = self.workers.local_worker.policy
        obs_b = np.asarray(obs, np.float32)[None]
        if not explore and hasattr(pol, "deterministic_actions"):
            a = np.asarray(pol.deterministic_actions(obs_b))[0]
            return int(a) if a.ndim == 0 else a
        action = pol.compute_actions(obs_b)[0]
        a = np.asarray(action)[0]
        return int(a) if a.ndim == 0 else a

    def evaluate(self) -> Dict:
        """Run evaluation_duration episodes with exploration off on a
        fresh env (reference: Algorithm.evaluate + the separate
        evaluation worker config); returns {"evaluation": {...}}.
        Single-agent only (multi-agent envs return per-agent obs dicts
        this loop doesn't speak)."""
        if self.is_multi_agent:
            raise NotImplementedError(
                "evaluate() is single-agent only in this framework")
        cfg = dict(self.algo_config)
        cfg.update(cfg.get("evaluation_config") or {})
        n = int(cfg.get("evaluation_duration", 10))
        max_steps = int(cfg.get("evaluation_max_steps", 1000))
        env = _default_env_creator(cfg)
        lw = self.workers.local_worker
        rewards, lens = [], []
        for ep in range(n):
            obs, _ = env.reset(seed=cfg.get("seed", 0) + 10_000 + ep)
            total, steps, done = 0.0, 0, False
            while not done and steps < max_steps:
                a = self.compute_single_action(
                    lw._obs_pipe(obs),
                    explore=bool(cfg.get("evaluation_explore", False)))
                a = lw._act_pipe(a)
                obs, r, term, trunc, _ = env.step(a)
                total += float(r)
                steps += 1
                done = bool(term) or bool(trunc)
            rewards.append(total)
            lens.append(steps)
        try:
            env.close()
        except Exception:
            pass
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
            "episode_len_mean": float(np.mean(lens)),
            "episodes_this_eval": n,
        }}

    def save_checkpoint(self) -> Dict:
        return {"weights": self.workers.local_worker.get_weights(),
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, data) -> None:
        if data:
            self.workers.local_worker.set_weights(data["weights"])
            self._timesteps_total = data.get("timesteps_total", 0)

    def cleanup(self):
        if self.policy_server is not None:
            try:
                self.policy_server.shutdown()
            except Exception:
                pass
        self.workers.stop()

    # Convenience parity with the reference's `algo.train()` usage outside
    # Tune: Trainable.train already works; expose stop() alias.
    def stop(self):
        super().stop()
