"""rllib: reinforcement learning on ray_tpu (scoped per SURVEY.md §7
phase 8: Algorithm-on-Trainable, WorkerSet of rollout actors, SampleBatch,
PPO + IMPALA with jax/flax policies)."""

from ray_tpu.rllib.algorithms.algorithm import (  # noqa: F401
    Algorithm,
    AlgorithmConfig,
)
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala.impala import (  # noqa: F401
    Impala,
    ImpalaConfig,
)
from ray_tpu.rllib.algorithms.ddppo.ddppo import (  # noqa: F401
    DDPPO,
    DDPPOConfig,
)
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.policy.sample_batch import SampleBatch  # noqa: F401

__all__ = ["Algorithm", "AlgorithmConfig", "DDPPO", "DDPPOConfig",
           "DQN", "DQNConfig", "Impala", "ImpalaConfig", "PPO",
           "PPOConfig", "SampleBatch"]
