"""rllib: reinforcement learning on ray_tpu (scoped per SURVEY.md §7
phase 8: Algorithm-on-Trainable, WorkerSet of rollout actors, SampleBatch,
PPO + IMPALA with jax/flax policies)."""

from ray_tpu.rllib.algorithms.algorithm import (  # noqa: F401
    Algorithm,
    AlgorithmConfig,
)
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala.impala import (  # noqa: F401
    Impala,
    ImpalaConfig,
)
from ray_tpu.rllib.algorithms.ddppo.ddppo import (  # noqa: F401
    DDPPO,
    DDPPOConfig,
)
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.apex_dqn.apex_dqn import (  # noqa: F401
    ApexDQN,
    ApexDQNConfig,
)
from ray_tpu.rllib.algorithms.a2c.a2c import A2C, A2CConfig  # noqa: F401
from ray_tpu.rllib.algorithms.appo.appo import (  # noqa: F401
    APPO,
    APPOConfig,
)
from ray_tpu.rllib.algorithms.es.es import ES, ESConfig  # noqa: F401
from ray_tpu.rllib.algorithms.pg.pg import PG, PGConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ddpg.ddpg import (  # noqa: F401
    DDPG,
    DDPGConfig,
)
from ray_tpu.rllib.algorithms.td3.td3 import TD3, TD3Config  # noqa: F401
from ray_tpu.rllib.algorithms.simple_q.simple_q import (  # noqa: F401
    SimpleQ,
    SimpleQConfig,
)
from ray_tpu.rllib.algorithms.cql.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.algorithms.a3c.a3c import A3C, A3CConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bandit.bandit import (  # noqa: F401
    BanditLinTS,
    BanditLinTSConfig,
    BanditLinUCB,
    BanditLinUCBConfig,
)
from ray_tpu.rllib.algorithms.marwil.marwil import (  # noqa: F401
    BC,
    BCConfig,
    MARWIL,
    MARWILConfig,
)
from ray_tpu.rllib.algorithms.ars.ars import ARS, ARSConfig  # noqa: F401
from ray_tpu.rllib.algorithms.crr.crr import CRR, CRRConfig  # noqa: F401
from ray_tpu.rllib.algorithms.slateq.slateq import (  # noqa: F401
    SlateQ,
    SlateQConfig,
)
from ray_tpu.rllib.algorithms.qmix.qmix import (  # noqa: F401
    QMix,
    QMixConfig,
)
from ray_tpu.rllib.algorithms.maddpg.maddpg import (  # noqa: F401
    MADDPG,
    MADDPGConfig,
)
from ray_tpu.rllib.algorithms.dt.dt import DT, DTConfig  # noqa: F401
from ray_tpu.rllib.algorithms.r2d2.r2d2 import (  # noqa: F401
    R2D2,
    R2D2Config,
)
from ray_tpu.rllib.algorithms.alpha_zero.alpha_zero import (  # noqa: F401
    AlphaZero,
    AlphaZeroConfig,
)
from ray_tpu.rllib.algorithms.maml.maml import MAML, MAMLConfig  # noqa: F401
from ray_tpu.rllib.algorithms.mbmpo.mbmpo import (  # noqa: F401
    MBMPO,
    MBMPOConfig,
)
from ray_tpu.rllib.algorithms.dreamer.dreamer import (  # noqa: F401
    Dreamer,
    DreamerConfig,
)
from ray_tpu.rllib.algorithms.alpha_star.alpha_star import (  # noqa: F401
    AlphaStar,
    AlphaStarConfig,
)
from ray_tpu.rllib.policy.sample_batch import SampleBatch  # noqa: F401

__all__ = ["A2C", "A2CConfig", "A3C", "A3CConfig", "APPO", "APPOConfig",
           "ARS", "ARSConfig", "Algorithm", "AlgorithmConfig",
           "AlphaStar", "AlphaStarConfig",
           "AlphaZero", "AlphaZeroConfig", "ApexDQN", "ApexDQNConfig",
           "BC", "BCConfig", "BanditLinTS", "BanditLinTSConfig",
           "BanditLinUCB", "BanditLinUCBConfig", "CQL", "CQLConfig",
           "CRR", "CRRConfig", "DDPG", "DDPGConfig", "DDPPO",
           "DDPPOConfig", "DQN", "DQNConfig", "DT", "DTConfig", "ES",
           "Dreamer", "DreamerConfig", "ESConfig", "Impala",
           "ImpalaConfig", "MADDPG", "MAML", "MAMLConfig",
           "MBMPO", "MBMPOConfig",
           "MADDPGConfig", "MARWIL", "MARWILConfig", "PG", "PGConfig",
           "PPO", "PPOConfig", "QMix", "QMixConfig", "R2D2",
           "R2D2Config", "SAC", "SACConfig", "SampleBatch", "SimpleQ",
           "SimpleQConfig", "SlateQ", "SlateQConfig", "TD3",
           "TD3Config"]

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("rllib")
del _rlu
