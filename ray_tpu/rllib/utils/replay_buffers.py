"""Replay buffers (reference: rllib/utils/replay_buffers — ring storage
with uniform sampling; the prioritized variant is scoped out)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._storage: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not self._storage:
            for k, v in batch.items():
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        for start in range(0, n, self.capacity):
            chunk = {k: v[start:start + self.capacity]
                     for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            idx = (self._next + np.arange(m)) % self.capacity
            for k, v in chunk.items():
                self._storage[k][idx] = v
            self._next = int((self._next + m) % self.capacity)
            self._size = int(min(self._size + m, self.capacity))

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.randint(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._storage.items()})
