"""Replay buffers (reference: rllib/utils/replay_buffers — ring storage
with uniform sampling, plus the proportional prioritized variant,
reference: rllib/utils/replay_buffers/prioritized_replay_buffer.py —
sum-tree sampling by TD-error priority with importance weights)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = capacity
        self._storage: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not self._storage:
            for k, v in batch.items():
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        for start in range(0, n, self.capacity):
            chunk = {k: v[start:start + self.capacity]
                     for k, v in batch.items()}
            m = len(next(iter(chunk.values())))
            idx = (self._next + np.arange(m)) % self.capacity
            for k, v in chunk.items():
                self._storage[k][idx] = v
            self._next = int((self._next + m) % self.capacity)
            self._size = int(min(self._size + m, self.capacity))

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.randint(0, self._size, size=batch_size)
        return SampleBatch({k: v[idx] for k, v in self._storage.items()})


def make_buffer(cfg: Dict, capacity_key: str = "buffer_capacity",
                capacity: Optional[int] = None,
                seed: Optional[int] = None) -> "ReplayBuffer":
    """Buffer from an algorithm config: the single seam for the
    prioritized-vs-uniform choice (used by DQN, DDPG/TD3, Ape-X)."""
    cap = capacity if capacity is not None else cfg[capacity_key]
    seed = seed if seed is not None else cfg.get("seed", 0)
    if cfg.get("prioritized_replay"):
        return PrioritizedReplayBuffer(
            cap, seed=seed,
            alpha=cfg.get("prioritized_replay_alpha", 0.6),
            beta=cfg.get("prioritized_replay_beta", 0.4))
    return ReplayBuffer(cap, seed=seed)


class _SumTree:
    """Flat-array binary sum tree over `capacity` leaves: O(log n)
    priority updates and prefix-sum sampling (reference:
    rllib/execution/segment_tree.py SumSegmentTree)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        # Round up to a power of two so leaves form one contiguous level.
        self._base = 1
        while self._base < capacity:
            self._base *= 2
        self._tree = np.zeros(2 * self._base, np.float64)

    def set(self, idx: np.ndarray, value: np.ndarray) -> None:
        pos = np.asarray(idx, np.int64) + self._base
        self._tree[pos] = value
        pos //= 2
        # Walk each touched path to the root; vectorized over the batch.
        while pos[0] >= 1:
            left = self._tree[2 * pos]
            right = self._tree[2 * pos + 1]
            self._tree[pos] = left + right
            pos = np.unique(pos // 2)
            if pos[0] == 0:
                break

    def total(self) -> float:
        return float(self._tree[1])

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self._tree[np.asarray(idx, np.int64) + self._base]

    def find_prefix(self, prefix: np.ndarray) -> np.ndarray:
        """For each prefix sum, the leaf index where it lands."""
        prefix = np.asarray(prefix, np.float64).copy()
        pos = np.ones(len(prefix), np.int64)
        while pos[0] < self._base:
            left = 2 * pos
            left_sum = self._tree[left]
            go_right = prefix > left_sum
            prefix = np.where(go_right, prefix - left_sum, prefix)
            pos = np.where(go_right, left + 1, left)
        return pos - self._base


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized experience replay (Schaul et al. 2016).

    sample() returns two extra columns: "weights" (importance-sampling
    corrections, normalized by the max weight) and "batch_indexes"
    (for update_priorities after the learner computes new TD errors).
    Reference: rllib/utils/replay_buffers/prioritized_replay_buffer.py.
    """

    def __init__(self, capacity: int = 100_000, seed: int = 0,
                 alpha: float = 0.6, beta: float = 0.4,
                 eps: float = 1e-6):
        super().__init__(capacity, seed)
        assert alpha >= 0
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._tree = _SumTree(capacity)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        first = self._next
        super().add(batch)
        # New samples get max priority so everything is seen at least
        # once before TD errors take over.
        idx = (first + np.arange(min(n, self.capacity))) % self.capacity
        self._tree.set(idx, np.full(len(idx),
                                    self._max_priority ** self.alpha))

    def sample(self, batch_size: int, beta: Optional[float] = None
               ) -> SampleBatch:
        beta = self.beta if beta is None else beta
        total = self._tree.total()
        # Stratified prefix sampling across the mass.
        seg = total / batch_size
        prefix = (np.arange(batch_size) + self._rng.rand(batch_size)) * seg
        idx = np.minimum(self._tree.find_prefix(prefix), self._size - 1)
        prios = np.maximum(self._tree.get(idx), 1e-12)
        probs = prios / total
        weights = (self._size * probs) ** (-beta)
        weights = weights / weights.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx.astype(np.int64)
        return SampleBatch(out)

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(np.asarray(td_errors, np.float64)) + self.eps
        self._max_priority = max(self._max_priority, float(prios.max()))
        self._tree.set(np.asarray(idx, np.int64), prios ** self.alpha)
