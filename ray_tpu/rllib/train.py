"""`rllib train`-style CLI: run an algorithm from a declarative config.

Reference: rllib/train.py (+ rllib/tuned_examples/*.yaml, the
learning-regression configs CI replays).  A config file (JSON, or YAML
when pyyaml is present) names the algorithm, its config overrides, and
stop criteria:

    {"run": "PPO",
     "env": "CartPole-v1",
     "config": {"num_rollout_workers": 2, "lr": 3e-4},
     "stop": {"episode_reward_mean": 150, "training_iteration": 40}}

Usage:
    python -m ray_tpu.rllib.train -f rllib/tuned_examples/<name>.json
    python -m ray_tpu.rllib.train --run DQN --env CartPole-v1 \
        --stop-reward 100

Exit code 0 iff every stop criterion that names a metric bar was MET
(not merely timed out) — so a directory of tuned_examples doubles as a
learning-regression battery:

    for f in rllib/tuned_examples/*.json; do
        python -m ray_tpu.rllib.train -f "$f" || exit 1
    done
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict


def load_config(path: str) -> Dict:
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml
            return yaml.safe_load(text)
        except ImportError:
            raise ValueError(
                f"{path} is not JSON and pyyaml is unavailable")


def _resolve_algo(run: str):
    import ray_tpu.rllib as rl
    cfg_cls = getattr(rl, f"{run}Config", None)
    if cfg_cls is None:
        names = sorted(n[:-6] for n in rl.__all__ if n.endswith("Config"))
        raise SystemExit(f"unknown algorithm {run!r}; available: {names}")
    return cfg_cls


def run_experiment(spec: Dict, quiet: bool = False) -> bool:
    """Run one tuned-example spec; True iff metric bars were met."""
    import ray_tpu
    started = False
    if not ray_tpu.is_initialized():
        # Algorithms are cluster citizens (rollout workers are actors);
        # bring up a local runtime like `rllib train` does.
        ray_tpu.init(ignore_reinit_error=True)
        started = True
    try:
        return _run_experiment_inner(spec, quiet)
    finally:
        if started:
            ray_tpu.shutdown()


def _run_experiment_inner(spec: Dict, quiet: bool) -> bool:
    cfg_cls = _resolve_algo(spec["run"])
    builder = cfg_cls()
    if spec.get("env") is not None and hasattr(builder, "environment"):
        builder.environment(spec["env"],
                            spec.get("env_config") or None)
    builder.training(**(spec.get("config") or {}))
    if spec.get("seed") is not None:
        builder.debugging(seed=spec["seed"])
    algo = builder.build()
    stop = dict(spec.get("stop") or {})
    max_iters = int(stop.pop("training_iteration", 100))
    bars = stop  # every remaining key is a metric >= bar
    met = not bars
    try:
        for i in range(max_iters):
            result = algo.train()
            if not quiet:
                shown = {k: round(v, 2) for k, v in result.items()
                         if isinstance(v, (int, float))
                         and k in ("episode_reward_mean",
                                   "mixture_exploitability",
                                   "timesteps_total")}
                print(f"iter {i + 1}: {shown}", flush=True)
            if bars and all(
                    isinstance(result.get(k), (int, float))
                    and result[k] >= bar for k, bar in bars.items()):
                met = True
                break
    finally:
        try:
            algo.stop()
        except Exception:
            pass
    return met


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="rllib-train",
                                     description=__doc__.split("\n")[0])
    parser.add_argument("-f", "--file", help="JSON/YAML experiment spec")
    parser.add_argument("--run", help="algorithm name (e.g. PPO)")
    parser.add_argument("--env", help="gym env id")
    parser.add_argument("--stop-reward", type=float, default=None)
    parser.add_argument("--stop-iters", type=int, default=20)
    parser.add_argument("--config", default="{}",
                        help="JSON dict of algorithm config overrides")
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)

    if args.file:
        spec = load_config(args.file)
    elif args.run:
        spec = {"run": args.run, "env": args.env,
                "config": json.loads(args.config),
                "stop": {"training_iteration": args.stop_iters}}
        if args.stop_reward is not None:
            spec["stop"]["episode_reward_mean"] = args.stop_reward
    else:
        parser.error("need -f FILE or --run ALGO")
    ok = run_experiment(spec, quiet=args.quiet)
    print("PASSED" if ok else "FAILED: stop criteria not met")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
