"""`rllib train`-style CLI: run an algorithm from a declarative config.

Reference: rllib/train.py (+ rllib/tuned_examples/*.yaml, the
learning-regression configs CI replays).  A config file (JSON, or YAML
when pyyaml is present) names the algorithm, its config overrides, and
stop criteria:

    {"run": "PPO",
     "env": "CartPole-v1",
     "config": {"num_rollout_workers": 2, "lr": 3e-4},
     "stop": {"episode_reward_mean": 150, "training_iteration": 40}}

Usage:
    python -m ray_tpu.rllib.train -f rllib/tuned_examples/<name>.json
    python -m ray_tpu.rllib.train --run DQN --env CartPole-v1 \
        --stop-reward 100

Exit code 0 iff every stop criterion that names a metric bar was MET
(not merely timed out) — so a directory of tuned_examples doubles as a
learning-regression battery:

    for f in rllib/tuned_examples/*.json; do
        python -m ray_tpu.rllib.train -f "$f" || exit 1
    done
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict


def load_config(path: str) -> Dict:
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml
            return yaml.safe_load(text)
        except ImportError:
            raise ValueError(
                f"{path} is not JSON and pyyaml is unavailable")


def _resolve_algo(run: str):
    import ray_tpu.rllib as rl
    cfg_cls = getattr(rl, f"{run}Config", None)
    if cfg_cls is None:
        names = sorted(n[:-6] for n in rl.__all__ if n.endswith("Config"))
        raise SystemExit(f"unknown algorithm {run!r}; available: {names}")
    return cfg_cls


def run_experiment(spec: Dict, quiet: bool = False) -> bool:
    """Run one tuned-example spec; True iff metric bars were met."""
    import os

    import ray_tpu
    started = False
    if not ray_tpu.is_initialized():
        # Algorithms are cluster citizens (rollout workers are actors);
        # bring up a local runtime like `rllib train` does.  Logical
        # CPUs floor at 4: tuned examples assume a few rollout-worker
        # slots, and on a 1-core host the raylet would otherwise report
        # their resource demands infeasible (CPU here is a scheduling
        # token, not a pinned core).
        ray_tpu.init(num_cpus=int(os.environ.get(
            "RT_NUM_CPUS", max(4, os.cpu_count() or 1))),
            ignore_reinit_error=True)
        started = True
    try:
        return _run_experiment_inner(spec, quiet)
    finally:
        if started:
            ray_tpu.shutdown()


def _run_experiment_inner(spec: Dict, quiet: bool) -> bool:
    cfg_cls = _resolve_algo(spec["run"])
    builder = cfg_cls()
    if (spec.get("env") is not None or spec.get("env_config")) \
            and hasattr(builder, "environment"):
        builder.environment(spec.get("env"),
                            spec.get("env_config") or None)
    if spec.get("offline"):
        # Hermetic battery: generate the dataset the reference would
        # read from disk (offline/generators.py).
        from ray_tpu.rllib.offline.generators import generate
        builder.offline_data(generate(spec["offline"]))
    builder.training(**(spec.get("config") or {}))
    if spec.get("seed") is not None:
        builder.debugging(seed=spec["seed"])
    algo = builder.build()
    stop = dict(spec.get("stop") or {})
    max_iters = int(stop.pop("training_iteration", 100))
    bars = stop  # every remaining key is a metric >= bar
    # Lower-is-better bars (exploitability, model losses).
    bars_lte = dict(spec.get("stop_lte") or {})
    met = not bars and not bars_lte
    try:
        for i in range(max_iters):
            result = algo.train()
            if not quiet:
                shown = {k: round(v, 2) for k, v in result.items()
                         if isinstance(v, (int, float))
                         and k in ("episode_reward_mean",
                                   "episode_reward_this_iter",
                                   "mixture_exploitability",
                                   "timesteps_total")}
                print(f"iter {i + 1}: {shown}", flush=True)
            ge_ok = all(isinstance(result.get(k), (int, float))
                        and result[k] >= bar
                        for k, bar in bars.items())
            le_ok = all(isinstance(result.get(k), (int, float))
                        and result[k] <= bar
                        for k, bar in bars_lte.items())
            if (bars or bars_lte) and ge_ok and le_ok:
                met = True
                break
    finally:
        try:
            algo.stop()
        except Exception:
            pass
    return met


def run_battery(directory: str, include=None, quiet: bool = True) -> int:
    """Sweep every tuned example in ``directory`` (the regression
    battery the reference replays in CI from rllib/tuned_examples/ via
    rllib/BUILD learning-test targets).  Prints a PASS/FAIL table;
    exit code 0 iff every spec met its bars."""
    import glob
    import os
    import time as _time

    paths = sorted(glob.glob(os.path.join(directory, "*.json")))
    if include:
        wanted = set(include)
        paths = [p for p in paths
                 if os.path.splitext(os.path.basename(p))[0] in wanted]
        missing = wanted - {os.path.splitext(os.path.basename(p))[0]
                            for p in paths}
        if missing:
            raise SystemExit(f"no tuned example named: {sorted(missing)}")
    if not paths:
        raise SystemExit(f"no tuned examples under {directory}")
    rows = []
    failed = 0
    for p in paths:
        name = os.path.splitext(os.path.basename(p))[0]
        t0 = _time.monotonic()
        run = "?"
        try:
            # Inside the try: a malformed spec is THAT example's FAIL,
            # not a lost sweep.
            spec = load_config(p)
            run = spec["run"]
            ok = run_experiment(spec, quiet=quiet)
            err = ""
        except (KeyboardInterrupt, SystemExit):
            raise  # the operator's abort must abort the sweep
        except BaseException as e:  # a crash is a battery failure
            ok, err = False, f"{type(e).__name__}: {e}"
        rows.append((name, run, ok, _time.monotonic() - t0, err))
        failed += 0 if ok else 1
        print(f"[{len(rows)}/{len(paths)}] {name}: "
              f"{'PASS' if ok else 'FAIL'} ({rows[-1][3]:.0f}s)"
              + (f" {err}" if err else ""), flush=True)
    width = max(len(r[0]) for r in rows)
    print(f"\n{'example'.ljust(width)}  algo        result  seconds")
    for name, run, ok, dt, err in rows:
        print(f"{name.ljust(width)}  {run.ljust(10)}  "
              f"{'PASS' if ok else 'FAIL'}    {dt:7.1f}"
              + (f"  {err}" if err else ""))
    print(f"\n{len(rows) - failed}/{len(rows)} passed")
    return 1 if failed else 0


def main(argv=None) -> int:
    import os
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The sitecustomize TPU hook overrides JAX_PLATFORMS via
        # jax.config; re-pin cpu so a battery sweep on a TPU host never
        # dials the chip tunnel from the driver process (the tunnel can
        # block arbitrarily long when the chip is busy, wedging the
        # sweep; rollout/train workers are pinned by worker_main.py).
        from ray_tpu._private.jax_utils import ensure_cpu
        ensure_cpu()
    parser = argparse.ArgumentParser(prog="rllib-train",
                                     description=__doc__.split("\n")[0])
    parser.add_argument("-f", "--file", help="JSON/YAML experiment spec")
    parser.add_argument("--batch", metavar="DIR", default=None,
                        help="run EVERY tuned example in DIR as a "
                             "regression battery (table + exit code)")
    parser.add_argument("--include", nargs="*", default=None,
                        help="with --batch: only these example names")
    parser.add_argument("--run", help="algorithm name (e.g. PPO)")
    parser.add_argument("--env", help="gym env id")
    parser.add_argument("--stop-reward", type=float, default=None)
    parser.add_argument("--stop-iters", type=int, default=20)
    parser.add_argument("--config", default="{}",
                        help="JSON dict of algorithm config overrides")
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)

    if args.batch:
        return run_battery(args.batch, include=args.include,
                           quiet=args.quiet)
    if args.file:
        spec = load_config(args.file)
    elif args.run:
        spec = {"run": args.run, "env": args.env,
                "config": json.loads(args.config),
                "stop": {"training_iteration": args.stop_iters}}
        if args.stop_reward is not None:
            spec["stop"]["episode_reward_mean"] = args.stop_reward
    else:
        parser.error("need -f FILE or --run ALGO")
    ok = run_experiment(spec, quiet=args.quiet)
    print("PASSED" if ok else "FAILED: stop criteria not met")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
