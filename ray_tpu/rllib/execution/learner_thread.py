"""LearnerThread: overlaps SGD with sampling for async algorithms.

Reference: rllib/execution/learner_thread.py:17 — a thread draining an
in-queue of sample batches into learn_on_batch while the driver keeps
collecting rollouts.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Optional


class LearnerThread(threading.Thread):
    def __init__(self, policy, max_queue: int = 16):
        super().__init__(daemon=True, name="impala-learner")
        self.policy = policy
        self.inqueue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self.stopped = False
        self.stats: Dict = {}
        self.num_batches = 0
        self.num_steps_trained = 0
        self._lock = threading.Lock()

    def run(self):
        while not self.stopped:
            try:
                batch = self.inqueue.get(timeout=0.5)
            except queue.Empty:
                continue
            if batch is None:
                break
            with self._lock:
                self.stats = self.policy.learn_on_batch(batch)
                self.num_batches += 1
                self.num_steps_trained += batch.count

    def get_weights(self):
        with self._lock:
            return self.policy.get_weights()

    def stop(self):
        self.stopped = True
        try:
            self.inqueue.put_nowait(None)
        except queue.Full:
            pass
