"""JaxQPolicy: epsilon-greedy Q-learning policy with a target network.

Reference: rllib/algorithms/dqn/dqn_torch_policy.py (TD loss + target
net) re-derived in jax: the whole TD step (double-DQN target, huber
loss, adam update) is one jitted function.
"""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.policy import sample_batch as sb


class QNet(nn.Module):
    num_actions: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return nn.Dense(self.num_actions)(h)


class JaxQPolicy:
    def __init__(self, obs_dim: int, num_actions: int, config: Dict):
        self.config = config
        self.num_actions = num_actions
        self.model = QNet(num_actions=num_actions,
                          hiddens=tuple(config.get("fcnet_hiddens",
                                                   (64, 64))))
        rng = jax.random.PRNGKey(config.get("policy_seed",
                                            config.get("seed", 0)))
        self.params = self.model.init(
            rng, jnp.zeros((1, obs_dim), jnp.float32))
        self.target_params = self.params
        self.tx = optax.adam(config.get("lr", 1e-3))
        self.opt_state = self.tx.init(self.params)
        self.epsilon = config.get("initial_epsilon", 1.0)
        self._rng = np.random.RandomState(config.get("seed", 0) + 7)
        self._forward = jax.jit(self.model.apply)
        self._train_step = jax.jit(self._train_step_impl)

    # ------------------------------------------------------------ acting
    def compute_actions(self, obs: np.ndarray):
        """Epsilon-greedy; returns (actions, logp, vf) — logp/vf are
        placeholders so RolloutWorker's row schema stays uniform."""
        q = np.asarray(self._forward(self.params,
                                     jnp.asarray(obs, jnp.float32)))
        greedy = q.argmax(axis=-1)
        explore = self._rng.rand(len(greedy)) < self.epsilon
        random_a = self._rng.randint(0, self.num_actions, size=len(greedy))
        actions = np.where(explore, random_a, greedy)
        zeros = np.zeros(len(greedy), np.float32)
        return actions.astype(np.int64), zeros, zeros

    def value(self, obs: np.ndarray) -> np.ndarray:
        q = self._forward(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(q.max(axis=-1))

    # ---------------------------------------------------------- learning
    def _train_step_impl(self, params, target_params, opt_state, batch):
        gamma = self.config.get("gamma", 0.99)

        def loss_fn(p):
            q = self.model.apply(p, batch["obs"])
            qa = q[jnp.arange(q.shape[0]), batch["actions"]]
            q_next_target = self.model.apply(target_params,
                                             batch["new_obs"])
            if self.config.get("double_q", True):
                # Double DQN: online net picks, target net evaluates.
                q_next_online = self.model.apply(p, batch["new_obs"])
                next_a = q_next_online.argmax(axis=-1)
                q_next = q_next_target[jnp.arange(q.shape[0]), next_a]
            else:
                # Vanilla Q-learning target (reference: simple_q).
                q_next = q_next_target.max(axis=-1)
            target = batch["rewards"] + gamma * q_next * (
                1.0 - batch["dones"].astype(jnp.float32))
            td = qa - jax.lax.stop_gradient(target)
            # Importance-sampling weights from prioritized replay scale
            # each sample's loss (reference: dqn policy build_q_losses
            # PRIO_WEIGHTS); uniform replay passes all-ones.
            loss = (batch["weights"] * optax.huber_loss(td)).mean()
            return loss, td

        (loss, td), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, td, {"total_loss": loss,
                                       "mean_td_error": jnp.abs(td).mean()}

    _TRAIN_KEYS = ("obs", "actions", "rewards", "dones", "new_obs")

    def learn_on_batch(self, batch) -> Dict[str, float]:
        # Only the TD-loss inputs go to device; replay rows also carry
        # GAE fields (shared rollout schema) the Q loss never reads.
        jbatch = {k: jnp.asarray(batch[k]) for k in self._TRAIN_KEYS}
        n = len(batch["obs"])
        jbatch["weights"] = (jnp.asarray(batch["weights"], jnp.float32)
                             if "weights" in batch
                             else jnp.ones(n, jnp.float32))
        self.params, self.opt_state, td, stats = self._train_step(
            self.params, self.target_params, self.opt_state, jbatch)
        out = {k: float(v) for k, v in stats.items()}
        # Per-sample TD errors drive priority updates in prioritized
        # replay (reference: prio feedback loop in dqn training_step).
        self.last_td_errors = np.asarray(td)
        return out

    def update_target(self):
        self.target_params = self.params

    # ----------------------------------------------------------- weights
    def get_weights(self):
        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "epsilon": self.epsilon}

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray,
                                             weights["params"])
        self.epsilon = weights["epsilon"]
