"""JaxPolicy: categorical-action policy with a jitted PPO-style train step.

Reference: rllib/policy/policy.py:150 (Policy API: compute_actions /
learn_on_batch / get_weights / set_weights) — re-designed jax-first: the
entire SGD step (forward, loss, grad, adam update) is one jitted function;
weights cross process boundaries as numpy pytrees through the object
store.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.models.catalog import FCPolicyValueNet
from ray_tpu.rllib.policy import sample_batch as sb


class JaxPolicy:
    def __init__(self, obs_dim: int, num_actions: int, config: Dict):
        self.config = config
        self.model = FCPolicyValueNet(
            num_actions=num_actions,
            hiddens=tuple(config.get("fcnet_hiddens", (64, 64))))
        rng = jax.random.PRNGKey(config.get("seed", 0))
        self.params = self.model.init(
            rng, jnp.zeros((1, obs_dim), jnp.float32))
        self.tx = optax.adam(config.get("lr", 3e-4))
        self.opt_state = self.tx.init(self.params)
        self._rng = jax.random.PRNGKey(config.get("seed", 0) + 1)
        self._forward = jax.jit(self.model.apply)
        self._mesh = None
        self._train_step = None

    def _ensure_train_step(self):
        """Build the (possibly dp-sharded) SGD step on first use.

        Multi-chip learner (reference: the multi-GPU tower stack,
        rllib/execution/multi_gpu_learner_thread.py — re-designed as
        SPMD): config["learner_dp"] > 1 shards each SGD minibatch over a
        dp mesh; params/opt replicate, XLA inserts the gradient psum.
        Same math as single-chip (oracle-tested).  Built lazily so
        sampling-only rollout workers — whose hosts may not even have
        learner_dp devices — never construct the mesh."""
        if self._train_step is not None:
            return
        dp = int(self.config.get("learner_dp", 0) or 0)
        if dp > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ray_tpu.parallel.mesh import MeshSpec, make_mesh
            self._mesh = make_mesh(MeshSpec(dp=dp))
            batch_sh = NamedSharding(self._mesh, P("dp"))
            repl = NamedSharding(self._mesh, P())
            self._train_step = jax.jit(
                self._train_step_impl,
                in_shardings=(repl, repl, batch_sh),
                out_shardings=(repl, repl, repl))
        else:
            self._train_step = jax.jit(self._train_step_impl)

    # ------------------------------------------------------------ acting
    def compute_actions(self, obs: np.ndarray) \
            -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns (actions, action_logp, vf_preds)."""
        self._rng, key = jax.random.split(self._rng)
        logits, value = self._forward(self.params,
                                      jnp.asarray(obs, jnp.float32))
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), actions]
        return (np.asarray(actions), np.asarray(logp), np.asarray(value))

    def value(self, obs: np.ndarray) -> np.ndarray:
        _, v = self._forward(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(v)

    def deterministic_actions(self, obs: np.ndarray) -> np.ndarray:
        """Greedy (argmax) actions — the evaluation path."""
        logits, _ = self._forward(self.params,
                                  jnp.asarray(obs, jnp.float32))
        return np.asarray(jnp.argmax(logits, axis=-1))

    # ---------------------------------------------------------- learning
    def _loss(self, params, batch):
        """PPO clip objective, or IMPALA's importance-clipped policy
        gradient when config["loss"] == "impala" (reference:
        rllib/algorithms/ppo/ppo_torch_policy.py loss; impala vtrace rho
        truncation — scoped to the rho-clipped advantage form)."""
        cfg = self.config
        logits, value = self.model.apply(params, batch[sb.OBS])
        logp_all = jax.nn.log_softmax(logits)
        logp = logp_all[jnp.arange(logits.shape[0]), batch[sb.ACTIONS]]
        ratio = jnp.exp(logp - batch[sb.ACTION_LOGP])
        adv = batch[sb.ADVANTAGES]
        if cfg.get("loss", "ppo") == "impala":
            # Off-policy correction: truncated importance weights (the
            # rho-bar of V-trace) applied to the advantage estimate.
            rho = jnp.minimum(jax.lax.stop_gradient(ratio),
                              cfg.get("rho_clip", 1.0))
            surrogate = rho * adv * logp
        else:
            clip = cfg.get("clip_param", 0.2)
            surrogate = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        vf_loss = (value - batch[sb.VALUE_TARGETS]) ** 2
        total = (-surrogate.mean()
                 + cfg.get("vf_loss_coeff", 0.5) * vf_loss.mean()
                 - cfg.get("entropy_coeff", 0.0) * entropy.mean())
        return total, {"policy_loss": -surrogate.mean(),
                       "vf_loss": vf_loss.mean(),
                       "entropy": entropy.mean()}

    def _train_step_impl(self, params, opt_state, batch):
        (loss, stats), grads = jax.value_and_grad(
            self._loss, has_aux=True)(params, batch)
        updates, opt_state = self.tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats = dict(stats, total_loss=loss)
        return params, opt_state, stats

    def learn_on_batch(self, batch: sb.SampleBatch) -> Dict[str, float]:
        self._ensure_train_step()
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self._mesh is not None:
            # Exact-parity contract with the single-chip learner: rows
            # must shard evenly over dp (silent trimming would change
            # the gradient).
            dp = self._mesh.devices.size
            rows = next(iter(jbatch.values())).shape[0]
            if rows % dp != 0:
                raise ValueError(
                    f"minibatch of {rows} rows does not divide over "
                    f"learner_dp={dp}; pick sgd_minibatch_size as a "
                    f"multiple of learner_dp")
        self.params, self.opt_state, stats = self._train_step(
            self.params, self.opt_state, jbatch)
        return {k: float(v) for k, v in stats.items()}

    # Decentralized training (DD-PPO): grads out, reduced grads in.
    def compute_grads(self, batch: sb.SampleBatch):
        if not hasattr(self, "_grad_step"):
            def _impl(params, jbatch):
                (loss, stats), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, jbatch)
                return grads, dict(stats, total_loss=loss)
            self._grad_step = jax.jit(_impl)
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        grads, stats = self._grad_step(self.params, jbatch)
        return grads, {k: float(v) for k, v in stats.items()}

    def apply_grads(self, grads):
        updates, self.opt_state = self.tx.update(grads, self.opt_state,
                                                 self.params)
        self.params = optax.apply_updates(self.params, updates)

    # ----------------------------------------------------------- weights
    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
