"""JaxCRRPolicy: critic-regularized regression for offline RL.

Reference: rllib/algorithms/crr/torch/crr_torch_policy.py — a Gaussian
actor trained by advantage-weighted behavior cloning (weights
`1[A>0]` binary or `exp(A/beta)` exponential, advantage estimated as
Q(s,a) - mean_j Q(s, a_j~pi)) and a twin-Q critic trained by TD against
the target actor.  Re-derived jax-first: critic step, weighted-BC actor
step, and polyak target updates compile into one jitted train step.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.policy.jax_ddpg_policy import _CriticNet


class _GaussianActor(nn.Module):
    act_dim: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        mean = nn.Dense(self.act_dim)(h)
        log_std = self.param("log_std", nn.initializers.constant(-0.5),
                             (self.act_dim,))
        return jnp.tanh(mean), jnp.broadcast_to(
            jnp.clip(log_std, -5.0, 1.0), mean.shape)


class JaxCRRPolicy:
    supports_continuous = True

    def __init__(self, obs_dim: int, act_dim: int, config: Dict):
        if not config.get("_continuous"):
            raise TypeError("CRR requires a continuous (Box) action "
                            "space")
        self.config = config
        self.act_dim = act_dim
        low = np.asarray(config["_act_low"], np.float32)
        high = np.asarray(config["_act_high"], np.float32)
        self._scale = (high - low) / 2.0
        self._center = (high + low) / 2.0
        hiddens = tuple(config.get("fcnet_hiddens", (64, 64)))
        self.actor = _GaussianActor(act_dim=act_dim, hiddens=hiddens)
        self.q = _CriticNet(n_heads=2, hiddens=hiddens)
        rng = jax.random.PRNGKey(config.get("seed", 0))
        k1, k2, self._key = jax.random.split(rng, 3)
        zo = jnp.zeros((1, obs_dim), jnp.float32)
        za = jnp.zeros((1, act_dim), jnp.float32)
        self.actor_params = self.actor.init(k1, zo)
        self.q_params = self.q.init(k2, zo, za)
        self.target_actor_params = self.actor_params
        self.target_q_params = self.q_params
        self.actor_tx = optax.adam(config.get("lr", 3e-4))
        self.q_tx = optax.adam(config.get("critic_lr",
                                          config.get("lr", 3e-4)))
        self.actor_opt = self.actor_tx.init(self.actor_params)
        self.q_opt = self.q_tx.init(self.q_params)
        self._forward = jax.jit(self.actor.apply)
        self._train = jax.jit(self._train_impl)

    # ------------------------------------------------------------ acting
    def compute_actions(self, obs: np.ndarray):
        mean, _ = self._forward(self.actor_params,
                                jnp.asarray(obs, jnp.float32))
        act = np.asarray(mean) * self._scale + self._center
        zeros = np.zeros(len(act), np.float32)
        return act.astype(np.float32), zeros, zeros

    def value(self, obs: np.ndarray) -> np.ndarray:
        obs = jnp.asarray(obs, jnp.float32)
        mean, _ = self._forward(self.actor_params, obs)
        q1, _ = self.q.apply(self.q_params, obs, mean)
        return np.asarray(q1)

    # ---------------------------------------------------------- learning
    def _normalize(self, act):
        return (act - self._center) / self._scale

    def _train_impl(self, actor_params, q_params, ta_params, tq_params,
                    actor_opt, q_opt, key, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        tau = cfg.get("tau", 0.995)
        n_act = cfg.get("crr_n_action_samples", 4)
        beta = cfg.get("crr_beta", 1.0)
        binary = cfg.get("crr_weight_type", "bin") == "bin"
        obs, act = batch["obs"], batch["actions"]
        key, k_next, k_adv = jax.random.split(key, 3)

        # ---- critic: TD against target nets, next action ~ target pi.
        def q_loss_fn(qp):
            next_mean, next_log_std = self.actor.apply(ta_params,
                                                       batch["new_obs"])
            eps = jax.random.normal(k_next, next_mean.shape)
            next_a = jnp.clip(next_mean + eps * jnp.exp(next_log_std),
                              -1.0, 1.0)
            tq1, tq2 = self.q.apply(tq_params, batch["new_obs"], next_a)
            target = batch["rewards"] + gamma * jnp.minimum(tq1, tq2) * (
                1.0 - batch["dones"].astype(jnp.float32))
            q1, q2 = self.q.apply(qp, obs, act)
            t = jax.lax.stop_gradient(target)
            return ((q1 - t) ** 2 + (q2 - t) ** 2).mean()

        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(q_params)
        q_updates, q_opt = self.q_tx.update(q_grads, q_opt, q_params)
        q_params = optax.apply_updates(q_params, q_updates)

        # ---- advantage: Q(s,a_data) - mean_j Q(s, a_j ~ pi(s)).
        mean, log_std = self.actor.apply(actor_params, obs)
        eps = jax.random.normal(
            k_adv, (n_act,) + mean.shape)
        sampled = jnp.clip(mean[None] + eps * jnp.exp(log_std)[None],
                           -1.0, 1.0)
        q_pi = jnp.stack([
            jnp.minimum(*self.q.apply(q_params, obs, sampled[j]))
            for j in range(n_act)]).mean(axis=0)
        q_data = jnp.minimum(*self.q.apply(q_params, obs, act))
        adv = jax.lax.stop_gradient(q_data - q_pi)
        if binary:
            w = (adv > 0).astype(jnp.float32)
        else:
            w = jnp.minimum(jnp.exp(adv / beta), 20.0)

        # ---- actor: advantage-weighted log-likelihood of data actions.
        def actor_loss_fn(ap):
            m, ls = self.actor.apply(ap, obs)
            var = jnp.exp(2 * ls)
            logp = (-0.5 * ((act - m) ** 2 / var + 2 * ls
                            + jnp.log(2 * jnp.pi))).sum(axis=-1)
            return -(w * logp).mean()

        a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(actor_params)
        a_updates, actor_opt = self.actor_tx.update(a_grads, actor_opt,
                                                    actor_params)
        actor_params = optax.apply_updates(actor_params, a_updates)

        # ---- polyak targets.
        ta_params = jax.tree_util.tree_map(
            lambda t, p: tau * t + (1 - tau) * p, ta_params, actor_params)
        tq_params = jax.tree_util.tree_map(
            lambda t, p: tau * t + (1 - tau) * p, tq_params, q_params)
        stats = {"q_loss": q_loss, "actor_loss": a_loss,
                 "mean_advantage": adv.mean(),
                 "mean_weight": w.mean()}
        return (actor_params, q_params, ta_params, tq_params, actor_opt,
                q_opt, key, stats)

    def learn_on_batch(self, batch) -> Dict[str, float]:
        jb = {"obs": jnp.asarray(batch["obs"], jnp.float32),
              "actions": self._normalize(
                  jnp.asarray(batch["actions"], jnp.float32)),
              "rewards": jnp.asarray(batch["rewards"], jnp.float32),
              "dones": jnp.asarray(batch["dones"]),
              "new_obs": jnp.asarray(batch["new_obs"], jnp.float32)}
        (self.actor_params, self.q_params, self.target_actor_params,
         self.target_q_params, self.actor_opt, self.q_opt, self._key,
         stats) = self._train(
            self.actor_params, self.q_params, self.target_actor_params,
            self.target_q_params, self.actor_opt, self.q_opt, self._key,
            jb)
        return {k: float(v) for k, v in stats.items()}

    def update_target(self):
        pass  # polyak updates run inside the jitted train step

    # ----------------------------------------------------------- weights
    def get_weights(self):
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa
        return {"actor": to_np(self.actor_params),
                "q": to_np(self.q_params)}

    def set_weights(self, weights):
        to_j = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa
        self.actor_params = to_j(weights["actor"])
        self.q_params = to_j(weights["q"])
