"""JaxDDPGPolicy: deterministic-policy-gradient actor-critic, covering
both DDPG (Lillicrap et al. 2016) and TD3 (Fujimoto et al. 2018).

Reference: rllib/algorithms/ddpg/ddpg_torch_policy.py and
rllib/algorithms/td3/td3.py (TD3 = DDPG config preset with twin_q,
policy_delay=2, target-policy smoothing) — re-derived jax-first: the
critic update, (delayed) actor update, and polyak target updates run as
ONE jitted train step; the delay is a traced modulo counter so the
compiled program is identical every step.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax


class _ActorNet(nn.Module):
    act_dim: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        # tanh output in [-1, 1]; the policy rescales to the Box bounds.
        return jnp.tanh(nn.Dense(self.act_dim)(h))


class _CriticNet(nn.Module):
    """One or two Q(s, a) heads (twin critics are TD3's clipped
    double-Q trick)."""

    n_heads: int = 1
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        outs = []
        for _ in range(self.n_heads):
            h = x
            for width in self.hiddens:
                h = nn.relu(nn.Dense(width)(h))
            outs.append(nn.Dense(1)(h)[..., 0])
        return outs


class JaxDDPGPolicy:
    supports_continuous = True

    def __init__(self, obs_dim: int, act_dim: int, config: Dict):
        if not config.get("_continuous"):
            raise TypeError("DDPG/TD3 require a continuous (Box) action "
                            "space; use DQN/PPO for discrete envs")
        self.config = config
        self.act_dim = act_dim
        low = np.asarray(config.get("_act_low", -np.ones(act_dim)),
                         np.float32).reshape(-1)
        high = np.asarray(config.get("_act_high", np.ones(act_dim)),
                          np.float32).reshape(-1)
        if not (np.all(np.isfinite(low)) and np.all(np.isfinite(high))):
            raise ValueError("DDPG needs a bounded Box action space; got "
                             f"low={low} high={high}")
        self._low, self._high = low, high
        self._scale = jnp.asarray((high - low) / 2.0)
        self._mid = jnp.asarray((high + low) / 2.0)

        self.twin_q = bool(config.get("twin_q", False))
        self.policy_delay = int(config.get("policy_delay", 1))
        self.target_noise = float(config.get("target_noise", 0.0))
        self.target_noise_clip = float(config.get("target_noise_clip",
                                                  0.5))
        self.tau = float(config.get("tau", 0.995))
        self.explore_noise = float(config.get("exploration_noise", 0.1))

        hid = tuple(config.get("fcnet_hiddens", (64, 64)))
        self.actor = _ActorNet(act_dim=act_dim, hiddens=hid)
        self.critic = _CriticNet(n_heads=2 if self.twin_q else 1,
                                 hiddens=hid)
        rng = jax.random.PRNGKey(config.get("policy_seed",
                                            config.get("seed", 0)))
        k1, k2, self._rng = jax.random.split(rng, 3)
        dummy_o = jnp.zeros((1, obs_dim), jnp.float32)
        dummy_a = jnp.zeros((1, act_dim), jnp.float32)
        self.actor_params = self.actor.init(k1, dummy_o)
        self.critic_params = self.critic.init(k2, dummy_o, dummy_a)
        self.target_actor = self.actor_params
        self.target_critic = self.critic_params
        actor_lr = config.get("actor_lr", config.get("lr", 1e-3))
        critic_lr = config.get("critic_lr", config.get("lr", 1e-3))
        self.actor_tx = optax.adam(actor_lr)
        self.critic_tx = optax.adam(critic_lr)
        self.actor_opt = self.actor_tx.init(self.actor_params)
        self.critic_opt = self.critic_tx.init(self.critic_params)
        self._step_count = 0
        self._np_rng = np.random.RandomState(config.get("seed", 0) + 13)
        self._forward = jax.jit(self.actor.apply)
        self._train = jax.jit(self._train_impl,
                              static_argnames=("update_actor",))

    # --------------------------------------------------------- acting
    def _rescale(self, a):
        return a * self._scale + self._mid

    def compute_actions(self, obs: np.ndarray):
        """Deterministic action + Gaussian exploration noise (the
        reference's OU noise is near-equivalent at these scales and
        Gaussian is TD3's choice)."""
        a = np.asarray(self._forward(self.actor_params,
                                     jnp.asarray(obs, jnp.float32)))
        noise = self._np_rng.randn(*a.shape) * self.explore_noise
        a = np.clip(a + noise, -1.0, 1.0)
        a = np.asarray(self._rescale(jnp.asarray(a)), np.float32)
        zeros = np.zeros(len(obs), np.float32)
        return a, zeros, zeros

    def deterministic_actions(self, obs: np.ndarray) -> np.ndarray:
        """Noise-free actor output (evaluation path —
        Algorithm.compute_single_action(explore=False))."""
        a = np.asarray(self._forward(self.actor_params,
                                     jnp.asarray(obs, jnp.float32)))
        a = np.clip(a, -1.0, 1.0)
        return np.asarray(self._rescale(jnp.asarray(a)), np.float32)

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)

    # ------------------------------------------------------- learning
    def _train_impl(self, actor_params, critic_params, target_actor,
                    target_critic, actor_opt, critic_opt, batch, key,
                    update_actor: bool):
        gamma = self.config.get("gamma", 0.99)
        obs = batch["obs"]
        acts = batch["actions"]
        rew = batch["rewards"]
        done = batch["dones"].astype(jnp.float32)
        nobs = batch["new_obs"]

        # Target action with TD3's target-policy smoothing (zero noise
        # degrades to vanilla DDPG).
        na = self.actor.apply(target_actor, nobs)
        if self.target_noise > 0.0:
            eps = jnp.clip(
                jax.random.normal(key, na.shape) * self.target_noise,
                -self.target_noise_clip, self.target_noise_clip)
            na = jnp.clip(na + eps, -1.0, 1.0)
        tq = self.critic.apply(target_critic, nobs, self._rescale(na))
        q_next = jnp.minimum(*tq) if self.twin_q else tq[0]
        td_target = jax.lax.stop_gradient(
            rew + gamma * (1.0 - done) * q_next)

        def critic_loss_fn(cp):
            qs = self.critic.apply(cp, obs, acts)
            # Importance-sampling weights from prioritized replay
            # (all-ones under uniform replay).
            w = batch["weights"]
            loss = sum((w * (q - td_target) ** 2).mean() for q in qs)
            return loss, qs[0] - td_target

        (c_loss, td_err), c_grads = jax.value_and_grad(
            critic_loss_fn, has_aux=True)(critic_params)
        c_updates, critic_opt = self.critic_tx.update(
            c_grads, critic_opt, critic_params)
        critic_params = optax.apply_updates(critic_params, c_updates)

        def actor_loss_fn(ap):
            a = self.actor.apply(ap, obs)
            q = self.critic.apply(critic_params, obs, self._rescale(a))[0]
            return -q.mean()

        if update_actor:
            a_loss, a_grads = jax.value_and_grad(actor_loss_fn)(
                actor_params)
            a_updates, actor_opt = self.actor_tx.update(
                a_grads, actor_opt, actor_params)
            actor_params = optax.apply_updates(actor_params, a_updates)
            # Polyak targets move only with the actor (TD3 delays both).
            tau = self.tau
            target_actor = jax.tree_util.tree_map(
                lambda t, o: tau * t + (1 - tau) * o, target_actor,
                actor_params)
            target_critic = jax.tree_util.tree_map(
                lambda t, o: tau * t + (1 - tau) * o, target_critic,
                critic_params)
        else:
            a_loss = jnp.float32(0.0)
        return (actor_params, critic_params, target_actor, target_critic,
                actor_opt, critic_opt,
                {"critic_loss": c_loss, "actor_loss": a_loss,
                 "mean_td_error": jnp.abs(td_err).mean()}, td_err)

    def learn_on_batch(self, batch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(batch[k])
                  for k in ("obs", "actions", "rewards", "dones",
                            "new_obs")}
        jbatch["weights"] = (
            jnp.asarray(batch["weights"], jnp.float32)
            if "weights" in batch
            else jnp.ones(len(batch["obs"]), jnp.float32))
        self._step_count += 1
        update_actor = (self._step_count % self.policy_delay) == 0
        self._rng, key = jax.random.split(self._rng)
        (self.actor_params, self.critic_params, self.target_actor,
         self.target_critic, self.actor_opt, self.critic_opt, stats,
         td_err) = self._train(
            self.actor_params, self.critic_params, self.target_actor,
            self.target_critic, self.actor_opt, self.critic_opt, jbatch,
            key, update_actor=update_actor)
        self.last_td_errors = np.asarray(td_err)
        return {k: float(v) for k, v in stats.items()}

    def update_target(self):
        """Targets update inside the train step (polyak); no-op kept for
        interface parity with the Q policies."""

    # -------------------------------------------------------- weights
    def get_weights(self):
        t = jax.tree_util.tree_map
        return {"actor": t(np.asarray, self.actor_params),
                "critic": t(np.asarray, self.critic_params),
                "target_actor": t(np.asarray, self.target_actor),
                "target_critic": t(np.asarray, self.target_critic)}

    def set_weights(self, weights):
        t = jax.tree_util.tree_map
        self.actor_params = t(jnp.asarray, weights["actor"])
        self.critic_params = t(jnp.asarray, weights["critic"])
        self.target_actor = t(jnp.asarray, weights["target_actor"])
        self.target_critic = t(jnp.asarray, weights["target_critic"])
