"""JaxSACPolicy: discrete-action soft actor-critic.

Reference: rllib/algorithms/sac/sac_torch_policy.py (twin soft-Q nets,
stochastic policy, entropy temperature alpha with automatic tuning) —
scoped to the discrete-action form (Christodoulou 2019, "SAC for
discrete action settings": expectations over the action simplex replace
the reparameterized sample).  jax-first: actor, twin critics, alpha and
all three adam updates run as ONE jitted train step, so each SGD
minibatch is a single fused device program.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.policy import sample_batch as sb


class _PiNet(nn.Module):
    num_actions: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        return nn.Dense(self.num_actions)(h)  # logits


class _TwinQNet(nn.Module):
    num_actions: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        outs = []
        for _ in range(2):
            h = x
            for width in self.hiddens:
                h = nn.relu(nn.Dense(width)(h))
            outs.append(nn.Dense(self.num_actions)(h))
        return outs[0], outs[1]


class JaxSACPolicy:
    def __init__(self, obs_dim: int, num_actions: int, config: Dict):
        self.config = config
        self.num_actions = num_actions
        hid = tuple(config.get("fcnet_hiddens", (64, 64)))
        self.pi = _PiNet(num_actions=num_actions, hiddens=hid)
        self.q = _TwinQNet(num_actions=num_actions, hiddens=hid)
        rng = jax.random.PRNGKey(config.get("seed", 0))
        k1, k2, self._rng = jax.random.split(rng, 3)
        dummy = jnp.zeros((1, obs_dim), jnp.float32)
        self.pi_params = self.pi.init(k1, dummy)
        self.q_params = self.q.init(k2, dummy)
        self.target_q_params = self.q_params
        # Entropy temperature: optimized in log space toward the target
        # entropy (a fraction of max entropy for discrete spaces).
        self.log_alpha = jnp.asarray(
            np.log(config.get("initial_alpha", 0.1)), jnp.float32)
        # Target entropy: a modest fraction of max entropy.  The 0.98
        # factor from the discrete-SAC paper pins the policy close to
        # uniform on small action spaces (log 2 = 0.69 nats); half of max
        # keeps exploration pressure without forbidding exploitation.
        self.target_entropy = config.get(
            "target_entropy", 0.5 * float(np.log(num_actions)))
        lr = config.get("lr", 3e-4)
        self.pi_tx = optax.adam(lr)
        self.q_tx = optax.adam(lr)
        self.a_tx = optax.adam(lr)
        self.pi_opt = self.pi_tx.init(self.pi_params)
        self.q_opt = self.q_tx.init(self.q_params)
        self.a_opt = self.a_tx.init(self.log_alpha)
        self._forward = jax.jit(self.pi.apply)
        self._train = jax.jit(self._train_impl)

    # ------------------------------------------------------------ acting
    def compute_actions(self, obs: np.ndarray):
        """Sample from the categorical policy; (actions, logp, vf)
        placeholders keep RolloutWorker's schema."""
        self._rng, key = jax.random.split(self._rng)
        logits = self._forward(self.pi_params,
                               jnp.asarray(obs, jnp.float32))
        actions = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), actions]
        zeros = np.zeros(len(obs), np.float32)
        return np.asarray(actions), np.asarray(logp), zeros

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)

    # ---------------------------------------------------------- learning
    def _train_impl(self, pi_params, q_params, target_q, log_alpha,
                    pi_opt, q_opt, a_opt, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        alpha = jnp.exp(log_alpha)
        obs, acts = batch[sb.OBS], batch[sb.ACTIONS]
        rew = batch[sb.REWARDS]
        done = batch[sb.DONES].astype(jnp.float32)
        nobs = batch[sb.NEXT_OBS]

        # Soft state value under the target critics:
        #   V(s') = E_pi [ min Q_target(s',a) - alpha log pi(a|s') ]
        next_logits = self.pi.apply(pi_params, nobs)
        next_p = jax.nn.softmax(next_logits)
        next_logp = jax.nn.log_softmax(next_logits)
        tq1, tq2 = self.q.apply(target_q, nobs)
        next_v = jnp.sum(
            next_p * (jnp.minimum(tq1, tq2) - alpha * next_logp), axis=-1)
        td_target = jax.lax.stop_gradient(
            rew + gamma * (1.0 - done) * next_v)

        def q_loss_fn(qp):
            q1, q2 = self.q.apply(qp, obs)
            idx = jnp.arange(obs.shape[0])
            l1 = ((q1[idx, acts] - td_target) ** 2).mean()
            l2 = ((q2[idx, acts] - td_target) ** 2).mean()
            return l1 + l2

        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(q_params)
        q_updates, q_opt = self.q_tx.update(q_grads, q_opt, q_params)
        q_params = optax.apply_updates(q_params, q_updates)

        def pi_loss_fn(pp):
            logits = self.pi.apply(pp, obs)
            p = jax.nn.softmax(logits)
            logp = jax.nn.log_softmax(logits)
            q1, q2 = self.q.apply(q_params, obs)
            qmin = jax.lax.stop_gradient(jnp.minimum(q1, q2))
            loss = jnp.sum(p * (alpha * logp - qmin), axis=-1).mean()
            entropy = -jnp.sum(p * logp, axis=-1).mean()
            return loss, entropy

        (pi_loss, entropy), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True)(pi_params)
        pi_updates, pi_opt = self.pi_tx.update(pi_grads, pi_opt,
                                               pi_params)
        pi_params = optax.apply_updates(pi_params, pi_updates)

        def alpha_loss_fn(la):
            return jnp.exp(la) * jax.lax.stop_gradient(
                entropy - self.target_entropy)

        a_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(log_alpha)
        a_updates, a_opt = self.a_tx.update(a_grad, a_opt, log_alpha)
        log_alpha = optax.apply_updates(log_alpha, a_updates)

        stats = {"q_loss": q_loss, "policy_loss": pi_loss,
                 "alpha_loss": a_loss, "alpha": jnp.exp(log_alpha),
                 "entropy": entropy, "total_loss": q_loss + pi_loss}
        return (pi_params, q_params, log_alpha, pi_opt, q_opt, a_opt,
                stats)

    def learn_on_batch(self, batch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        (self.pi_params, self.q_params, self.log_alpha, self.pi_opt,
         self.q_opt, self.a_opt, stats) = self._train(
            self.pi_params, self.q_params, self.target_q_params,
            self.log_alpha, self.pi_opt, self.q_opt, self.a_opt, jbatch)
        return {k: float(v) for k, v in stats.items()}

    def update_target(self, tau: float | None = None):
        """Polyak update of the target critics (tau=1 -> hard sync)."""
        tau = self.config.get("tau", 0.995) if tau is None else tau
        self.target_q_params = jax.tree_util.tree_map(
            lambda t, s: tau * t + (1.0 - tau) * s,
            self.target_q_params, self.q_params)

    # ----------------------------------------------------------- weights
    def get_weights(self):
        return {"pi": jax.tree_util.tree_map(np.asarray, self.pi_params),
                "q": jax.tree_util.tree_map(np.asarray, self.q_params)}

    def set_weights(self, weights):
        if "epsilon" in weights:  # schema parity with JaxQPolicy pushes
            weights = {k: v for k, v in weights.items()
                       if k != "epsilon"}
        self.pi_params = jax.tree_util.tree_map(jnp.asarray,
                                                weights["pi"])
        if "q" in weights:
            self.q_params = jax.tree_util.tree_map(jnp.asarray,
                                                   weights["q"])


class _GaussianPiNet(nn.Module):
    """Tanh-squashed diagonal Gaussian actor head."""

    act_dim: int
    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, x):
        h = x
        for width in self.hiddens:
            h = nn.relu(nn.Dense(width)(h))
        mean = nn.Dense(self.act_dim)(h)
        log_std = jnp.clip(nn.Dense(self.act_dim)(h), -10.0, 2.0)
        return mean, log_std


class _QSANet(nn.Module):
    """Twin Q(s, a) critics over concatenated state-action input."""

    hiddens: tuple = (64, 64)

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        outs = []
        for _ in range(2):
            h = x
            for width in self.hiddens:
                h = nn.relu(nn.Dense(width)(h))
            outs.append(nn.Dense(1)(h)[..., 0])
        return outs[0], outs[1]


class JaxSACGaussianPolicy:
    """Continuous-action SAC (the reference's primary SAC form,
    rllib/algorithms/sac/sac_torch_policy.py): tanh-squashed Gaussian
    reparameterized actor, twin Q(s,a), target entropy -act_dim.  The
    whole update (critics, actor, temperature, their three adam steps)
    is one jitted function."""

    supports_continuous = True

    def __init__(self, obs_dim: int, act_dim: int, config: Dict):
        self.config = config
        self.act_dim = act_dim
        low = np.asarray(config.get("_act_low", -np.ones(act_dim)),
                         np.float32).reshape(-1)
        high = np.asarray(config.get("_act_high", np.ones(act_dim)),
                          np.float32).reshape(-1)
        if not (np.all(np.isfinite(low)) and np.all(np.isfinite(high))):
            raise ValueError(
                "tanh-squashed SAC needs a bounded Box action space; "
                f"got low={low}, high={high} — wrap the env with a "
                "bounded action wrapper")
        self._scale = jnp.asarray((high - low) / 2.0)
        self._mid = jnp.asarray((high + low) / 2.0)
        hid = tuple(config.get("fcnet_hiddens", (64, 64)))
        self.pi = _GaussianPiNet(act_dim=act_dim, hiddens=hid)
        self.q = _QSANet(hiddens=hid)
        rng = jax.random.PRNGKey(config.get("seed", 0))
        k1, k2, self._rng = jax.random.split(rng, 3)
        dummy_o = jnp.zeros((1, obs_dim), jnp.float32)
        dummy_a = jnp.zeros((1, act_dim), jnp.float32)
        self.pi_params = self.pi.init(k1, dummy_o)
        self.q_params = self.q.init(k2, dummy_o, dummy_a)
        self.target_q_params = self.q_params
        self.log_alpha = jnp.asarray(
            np.log(config.get("initial_alpha", 0.1)), jnp.float32)
        self.target_entropy = config.get("target_entropy",
                                         -float(act_dim))
        lr = config.get("lr", 3e-4)
        self.pi_tx = optax.adam(lr)
        self.q_tx = optax.adam(lr)
        self.a_tx = optax.adam(lr)
        self.pi_opt = self.pi_tx.init(self.pi_params)
        self.q_opt = self.q_tx.init(self.q_params)
        self.a_opt = self.a_tx.init(self.log_alpha)
        self._sample_act = jax.jit(self._sample_act_impl)
        self._train = jax.jit(self._train_impl)

    # --------------------------------------------------------- sampling
    def _squash(self, u):
        return jnp.tanh(u) * self._scale + self._mid

    def _sample_logp(self, params, obs, key):
        """Reparameterized sample + tanh-corrected log-prob."""
        mean, log_std = self.pi.apply(params, obs)
        std = jnp.exp(log_std)
        u = mean + std * jax.random.normal(key, mean.shape)
        logp_u = jnp.sum(
            -0.5 * ((u - mean) / std) ** 2 - log_std
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)
        # Change of variables through tanh (+ the affine scale).
        logp = logp_u - jnp.sum(
            jnp.log(self._scale * (1 - jnp.tanh(u) ** 2) + 1e-6),
            axis=-1)
        return self._squash(u), logp

    def _sample_act_impl(self, params, obs, key):
        act, logp = self._sample_logp(params, obs, key)
        return act, logp

    def compute_actions(self, obs: np.ndarray):
        self._rng, key = jax.random.split(self._rng)
        act, logp = self._sample_act(self.pi_params,
                                     jnp.asarray(obs, jnp.float32), key)
        zeros = np.zeros(len(obs), np.float32)
        return np.asarray(act), np.asarray(logp), zeros

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)

    # --------------------------------------------------------- learning
    def _train_impl(self, pi_params, q_params, target_q, log_alpha,
                    pi_opt, q_opt, a_opt, batch, key):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        alpha = jnp.exp(log_alpha)
        obs = batch[sb.OBS]
        acts = batch[sb.ACTIONS]
        rew = batch[sb.REWARDS]
        done = batch[sb.DONES].astype(jnp.float32)
        nobs = batch[sb.NEXT_OBS]
        k1, k2, k3, k4 = jax.random.split(key, 4)

        next_a, next_logp = self._sample_logp(pi_params, nobs, k1)
        tq1, tq2 = self.q.apply(target_q, nobs, next_a)
        next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
        td_target = jax.lax.stop_gradient(
            rew + gamma * (1.0 - done) * next_v)

        # CQL(H) conservative penalty weight (reference:
        # rllib/algorithms/cql/cql_torch_policy.py): 0 = plain SAC.
        cql_w = float(cfg.get("cql_min_q_weight", 0.0))
        n_cql = int(cfg.get("cql_n_actions", 4))

        def q_loss_fn(qp):
            q1, q2 = self.q.apply(qp, obs, acts)
            loss = ((q1 - td_target) ** 2).mean() \
                + ((q2 - td_target) ** 2).mean()
            if cql_w > 0.0:
                # Push down logsumexp Q over sampled (OOD) actions while
                # holding up Q on dataset actions.
                B = obs.shape[0]
                rand_u = jax.random.uniform(
                    k3, (n_cql, B, self.act_dim), minval=-1.0,
                    maxval=1.0)
                rand_a = rand_u * self._scale + self._mid
                pi_a, _ = self._sample_logp(
                    pi_params, jnp.tile(obs, (n_cql, 1)), k4)
                pi_a = pi_a.reshape(n_cql, B, self.act_dim)
                cat = jnp.concatenate([rand_a, pi_a], axis=0)
                flat = cat.reshape(-1, self.act_dim)
                obs_rep = jnp.tile(obs, (2 * n_cql, 1))
                cq1, cq2 = self.q.apply(qp, obs_rep, flat)
                cq1 = cq1.reshape(2 * n_cql, B)
                cq2 = cq2.reshape(2 * n_cql, B)
                gap1 = (jax.scipy.special.logsumexp(cq1, axis=0).mean()
                        - q1.mean())
                gap2 = (jax.scipy.special.logsumexp(cq2, axis=0).mean()
                        - q2.mean())
                loss = loss + cql_w * (gap1 + gap2)
            return loss

        q_loss, q_grads = jax.value_and_grad(q_loss_fn)(q_params)
        q_updates, q_opt = self.q_tx.update(q_grads, q_opt, q_params)
        q_params = optax.apply_updates(q_params, q_updates)

        def pi_loss_fn(pp):
            a, logp = self._sample_logp(pp, obs, k2)
            q1, q2 = self.q.apply(q_params, obs, a)
            qmin = jnp.minimum(q1, q2)
            return (alpha * logp - qmin).mean(), logp.mean()

        (pi_loss, mean_logp), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True)(pi_params)
        pi_updates, pi_opt = self.pi_tx.update(pi_grads, pi_opt,
                                               pi_params)
        pi_params = optax.apply_updates(pi_params, pi_updates)

        def alpha_loss_fn(la):
            return -jnp.exp(la) * jax.lax.stop_gradient(
                mean_logp + self.target_entropy)

        a_loss, a_grad = jax.value_and_grad(alpha_loss_fn)(log_alpha)
        a_updates, a_opt = self.a_tx.update(a_grad, a_opt, log_alpha)
        log_alpha = optax.apply_updates(log_alpha, a_updates)

        stats = {"q_loss": q_loss, "policy_loss": pi_loss,
                 "alpha_loss": a_loss, "alpha": jnp.exp(log_alpha),
                 "entropy": -mean_logp,
                 "total_loss": q_loss + pi_loss}
        return (pi_params, q_params, log_alpha, pi_opt, q_opt, a_opt,
                stats)

    def learn_on_batch(self, batch) -> Dict[str, float]:
        self._rng, key = jax.random.split(self._rng)
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        (self.pi_params, self.q_params, self.log_alpha, self.pi_opt,
         self.q_opt, self.a_opt, stats) = self._train(
            self.pi_params, self.q_params, self.target_q_params,
            self.log_alpha, self.pi_opt, self.q_opt, self.a_opt,
            jbatch, key)
        return {k: float(v) for k, v in stats.items()}

    def update_target(self, tau: float | None = None):
        tau = self.config.get("tau", 0.995) if tau is None else tau
        self.target_q_params = jax.tree_util.tree_map(
            lambda t, s: tau * t + (1.0 - tau) * s,
            self.target_q_params, self.q_params)

    def get_weights(self):
        return {"pi": jax.tree_util.tree_map(np.asarray, self.pi_params),
                "q": jax.tree_util.tree_map(np.asarray, self.q_params)}

    def set_weights(self, weights):
        self.pi_params = jax.tree_util.tree_map(jnp.asarray,
                                                weights["pi"])
        if "q" in weights:
            self.q_params = jax.tree_util.tree_map(jnp.asarray,
                                                   weights["q"])


class SACPolicy:
    """Dispatching constructor: discrete envs get the categorical
    soft-Q policy, Box envs the tanh-Gaussian one (RolloutWorker marks
    continuous spaces with config['_continuous'])."""

    supports_continuous = True

    def __new__(cls, obs_dim: int, num_actions: int, config: Dict):
        if config.get("_continuous"):
            return JaxSACGaussianPolicy(obs_dim, num_actions, config)
        return JaxSACPolicy(obs_dim, num_actions, config)
