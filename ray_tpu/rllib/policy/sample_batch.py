"""SampleBatch: the unit of experience moving between rollout and train.

Reference: rllib/policy/sample_batch.py — a dict of parallel arrays with
concat/shuffle/minibatch helpers.  Kept as plain numpy so batches ride the
shm object store zero-copy; conversion to jax arrays happens once at the
learner (device put = single host→HBM transfer).
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "new_obs"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """dict[str, np.ndarray] with aligned first dimensions."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([b[k] for b in batches]) for k in keys})

    def shuffle(self, rng: np.random.RandomState) -> "SampleBatch":
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n - size + 1, size):
            yield SampleBatch({k: v[start:start + size]
                               for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})


def compute_gae(batch: SampleBatch, last_value: float, gamma: float,
                lam: float) -> SampleBatch:
    """Generalized advantage estimation over one (possibly truncated)
    rollout segment (reference: rllib/evaluation/postprocessing.py
    compute_advantages)."""
    rewards = batch[REWARDS]
    dones = batch[DONES].astype(np.float32)
    vf = batch[VF_PREDS]
    n = len(rewards)
    adv = np.zeros(n, dtype=np.float32)
    next_v = last_value
    next_adv = 0.0
    for t in range(n - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_v * nonterminal - vf[t]
        next_adv = delta + gamma * lam * nonterminal * next_adv
        adv[t] = next_adv
        next_v = vf[t]
    batch[ADVANTAGES] = adv
    batch[VALUE_TARGETS] = (adv + vf).astype(np.float32)
    return batch
