"""PolicyMap: the per-worker collection of named policies.

Reference: rllib/policy/policy_map.py — maps policy_id -> Policy, with a
policy_mapping_fn deciding which policy controls which agent.  Here every
policy is a jax policy instance; specs carry (obs_dim, num_actions,
config-overrides).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple


class PolicySpec:
    """What to build a policy from (reference: rllib PolicySpec)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 config: Optional[Dict] = None, policy_cls=None):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.config = dict(config or {})
        self.policy_cls = policy_cls


class PolicyMap(dict):
    """policy_id -> policy instance; builds lazily from specs."""

    def __init__(self, specs: Dict[str, PolicySpec], base_config: Dict,
                 default_policy_cls):
        super().__init__()
        self._specs = specs
        self._base = dict(base_config)
        self._default_cls = default_policy_cls
        for pid, spec in specs.items():
            cls = spec.policy_cls or default_policy_cls
            cfg = dict(self._base)
            cfg.update(spec.config)
            self[pid] = cls(spec.obs_dim, spec.num_actions, cfg)

    def get_weights(self) -> Dict[str, object]:
        return {pid: pol.get_weights() for pid, pol in self.items()}

    def set_weights(self, weights: Dict[str, object]):
        for pid, w in weights.items():
            if pid in self:
                self[pid].set_weights(w)


def default_policy_mapping_fn(agent_id, *args, **kwargs) -> str:
    return "default_policy"


Mapping = Callable[..., str]
