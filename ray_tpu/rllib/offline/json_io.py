"""Offline dataset IO: newline-delimited JSON sample batches.

Reference: rllib/offline/{json_writer,json_reader}.py — rollouts written
as JSON lines of column lists, read back for offline algorithms (BC /
MARWIL) and for sharing experience between clusters.  Workers write
through `output` (rollout config); readers shuffle across files.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time
from typing import Dict, Iterator, List, Optional, Union

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class JsonWriter:
    """Append sample batches to timestamped .json files (one JSON object
    per line, columns as lists; reference: offline/json_writer.py)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._f = None
        self._bytes = 0

    def _rotate(self):
        if self._f is not None:
            self._f.close()
        name = f"output-{time.strftime('%Y-%m-%d_%H-%M-%S')}" \
               f"_{os.getpid()}_{int(time.time()*1e3) % 100000}.json"
        self._f = open(os.path.join(self.path, name), "a")
        self._bytes = 0

    def write(self, batch: SampleBatch) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        line = json.dumps(row) + "\n"
        if self._f is None or self._bytes + len(line) > self.max_file_size:
            self._rotate()
        self._f.write(line)
        self._f.flush()
        self._bytes += len(line)

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


_DTYPES = {
    "obs": np.float32, "new_obs": np.float32, "actions": None,
    "rewards": np.float32, "dones": np.bool_, "action_logp": np.float32,
    "vf_preds": np.float32, "advantages": np.float32,
    "value_targets": np.float32,
}


def _to_batch(row: Dict) -> SampleBatch:
    out = {}
    for k, v in row.items():
        dtype = _DTYPES.get(k)
        arr = np.asarray(v, dtype) if dtype else np.asarray(v)
        if k == "actions" and arr.dtype.kind in "iu":
            arr = arr.astype(np.int32)
        out[k] = arr
    return SampleBatch(out)


class JsonReader:
    """Iterate sample batches from .json files or a glob/directory
    (reference: offline/json_reader.py — cycles forever, shuffling file
    order, so `next()` always yields)."""

    def __init__(self, inputs: Union[str, List[str]], seed: int = 0):
        if isinstance(inputs, str):
            if os.path.isdir(inputs):
                inputs = os.path.join(inputs, "*.json")
            self.files = sorted(_glob.glob(inputs))
        else:
            self.files = list(inputs)
        if not self.files:
            raise ValueError(f"no offline input files match {inputs!r}")
        self._rng = np.random.RandomState(seed)
        self._iter = self._rows()

    def _rows(self) -> Iterator[SampleBatch]:
        while True:
            order = list(self.files)
            self._rng.shuffle(order)
            for path in order:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield _to_batch(json.loads(line))

    def next(self) -> SampleBatch:
        return next(self._iter)

    def read_all(self) -> SampleBatch:
        """One pass over every file, concatenated (for fixed-dataset
        offline training)."""
        batches = []
        for path in self.files:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        batches.append(_to_batch(json.loads(line)))
        return SampleBatch.concat_samples(batches)


def read_sample_batches(inputs: Union[str, List[str]]) -> SampleBatch:
    return JsonReader(inputs).read_all()
