from ray_tpu.rllib.offline.json_io import (
    JsonReader,
    JsonWriter,
    read_sample_batches,
)

__all__ = ["JsonReader", "JsonWriter", "read_sample_batches"]
