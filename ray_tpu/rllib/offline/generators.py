"""Synthetic offline-dataset generators for tuned_examples (reference:
the reference's tuned examples reference datasets on disk, e.g.
rllib/tests/data/cartpole/large.json replayed through JsonReader; in a
hermetic environment the battery generates equivalent data instead).

Each generator is named in a tuned-example spec as
``"offline": {"generator": "<name>", ...kwargs}`` and returns whatever
the algorithm's ``offline_data()`` expects (a column dict, or an
episode list for sequence models like DT)."""

from __future__ import annotations

import numpy as np


def expert_cartpole(n_steps: int = 3000, seed: int = 0):
    """Heuristic expert: push the cart toward the falling pole — scores
    ~200 on CartPole-v1, far above the ~22 random baseline."""
    import gymnasium as gym
    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=seed)
    rows = {"obs": [], "actions": [], "rewards": [], "dones": []}
    for _ in range(n_steps):
        action = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0
        rows["obs"].append(obs)
        rows["actions"].append(action)
        obs, reward, terminated, truncated, _ = env.step(action)
        rows["rewards"].append(float(reward))
        rows["dones"].append(bool(terminated or truncated))
        if terminated or truncated:
            obs, _ = env.reset()
    env.close()
    return {"obs": np.asarray(rows["obs"], np.float32),
            "actions": np.asarray(rows["actions"], np.int32),
            "rewards": np.asarray(rows["rewards"], np.float32),
            "dones": np.asarray(rows["dones"], np.bool_)}


def pendulum_random(n_steps: int = 3000, seed: int = 0):
    """Uniform-random behavior policy on Pendulum-v1 with next-obs
    columns — the offline-RL (CQL/CRR) diet."""
    import gymnasium as gym
    rng = np.random.RandomState(seed)
    env = gym.make("Pendulum-v1")
    rows = {"obs": [], "actions": [], "rewards": [], "dones": [],
            "new_obs": []}
    obs, _ = env.reset(seed=seed)
    for _ in range(n_steps):
        a = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
        obs2, r, term, trunc, _ = env.step(a)
        rows["obs"].append(obs)
        rows["actions"].append(a)
        rows["rewards"].append(r)
        rows["dones"].append(term)
        rows["new_obs"].append(obs2)
        obs = obs2
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    return {k: np.asarray(v, np.float32 if k != "dones" else np.bool_)
            for k, v in rows.items()}


def cartpole_mixed_episodes(n_expert: int = 30, n_random: int = 30,
                            seed: int = 0):
    """Offline CartPole EPISODES: heuristic 'expert' (angle+angvel
    controller) episodes plus random ones — return-conditioned models
    (DT) must learn to imitate the GOOD episodes when conditioned on a
    high return-to-go."""
    import gymnasium as gym
    rng = np.random.RandomState(seed)
    env = gym.make("CartPole-v1")
    episodes = []
    for i in range(n_expert + n_random):
        expert = i < n_expert
        obs, _ = env.reset(seed=seed * 1000 + i)
        rows = {"obs": [], "actions": [], "rewards": []}
        for _ in range(200):
            if expert:
                a = int(obs[2] + 0.5 * obs[3] > 0)
            else:
                a = int(rng.randint(2))
            obs2, r, term, trunc, _ = env.step(a)
            rows["obs"].append(obs)
            rows["actions"].append(a)
            rows["rewards"].append(r)
            obs = obs2
            if term or trunc:
                break
        episodes.append({
            "obs": np.asarray(rows["obs"], np.float32),
            "actions": np.asarray(rows["actions"], np.int64),
            "rewards": np.asarray(rows["rewards"], np.float32)})
    env.close()
    return episodes


GENERATORS = {
    "expert_cartpole": expert_cartpole,
    "pendulum_random": pendulum_random,
    "cartpole_mixed_episodes": cartpole_mixed_episodes,
}


def generate(spec: dict):
    """Resolve an ``"offline"`` tuned-example block to a dataset."""
    spec = dict(spec)
    name = spec.pop("generator")
    try:
        fn = GENERATORS[name]
    except KeyError:
        raise ValueError(
            f"unknown offline generator {name!r}; "
            f"available: {sorted(GENERATORS)}")
    return fn(**spec)
