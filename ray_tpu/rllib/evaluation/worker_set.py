"""WorkerSet: the gang of RolloutWorker actors.

Reference: rllib/evaluation/worker_set.py:50 — remote workers + a local
worker for the learner; sync_weights broadcasts through the object store
(one put, N fetches — the reference's object-store broadcast pattern).
"""

from __future__ import annotations

from typing import Callable, Dict, List

import ray_tpu
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker


class WorkerSet:
    def __init__(self, env_creator: Callable, policy_cls, config: Dict,
                 num_workers: int, worker_cls=None):
        self.config = config
        worker_cls = worker_cls or RolloutWorker
        # Local worker holds the learner policy (reference: WorkerSet
        # local_worker()).
        self.local_worker = worker_cls(env_creator, policy_cls, config,
                                       worker_index=0)
        remote_cls = ray_tpu.remote(worker_cls)
        self.remote_workers = [
            remote_cls.options(num_cpus=1).remote(
                env_creator, policy_cls, config, worker_index=i + 1)
            for i in range(num_workers)
        ]

    def sync_weights(self):
        """Broadcast learner weights: one shm put, each worker fetches."""
        ref = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(ref)
                     for w in self.remote_workers], timeout=300)

    def sample_all(self, num_steps: int) -> List:
        """One sample() round per remote worker (refs, not values)."""
        return [w.sample.remote(num_steps) for w in self.remote_workers]

    def episode_stats(self) -> Dict:
        stats = ray_tpu.get([w.episode_stats.remote()
                             for w in self.remote_workers], timeout=300)
        local = self.local_worker.episode_stats()
        rewards = list(local["episode_rewards"])
        lens = list(local["episode_lens"])
        for s in stats:
            rewards += s["episode_rewards"]
            lens += s["episode_lens"]
        return {"episode_rewards": rewards, "episode_lens": lens}

    def stop(self):
        # All stop() calls in flight before draining: a get() per
        # worker inside the submit loop serializes the shutdowns.
        stops = []
        for w in self.remote_workers:
            try:
                stops.append((w, w.stop.remote()))
            except Exception:
                stops.append((w, None))
        for w, ref in stops:
            try:
                if ref is not None:
                    ray_tpu.get(ref, timeout=10)
                ray_tpu.kill(w)
            except Exception:
                pass
        self.remote_workers = []
