"""RolloutWorker: an actor stepping environments with the current policy.

Reference: rllib/evaluation/rollout_worker.py:124 (sample :776) — env
loop + policy inference + GAE postprocessing.  Workers are CPU actors;
the learner (driver or TPU actor) trains and broadcasts weights back.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.policy import sample_batch as sb
from ray_tpu.rllib.policy.sample_batch import SampleBatch, compute_gae
from ray_tpu.util.collective.collective import CollectiveMixin


class RolloutWorker(CollectiveMixin):
    def __init__(self, env_creator: Callable, policy_cls, config: Dict,
                 worker_index: int = 0):
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.config = dict(config)
        self.config["seed"] = self.config.get("seed", 0) + worker_index
        self.env = env_creator(self.config)
        obs_dim = int(np.prod(self.env.observation_space.shape))
        space = self.env.action_space
        if hasattr(space, "n"):  # Discrete
            self._discrete = True
            num_actions = int(space.n)
        else:  # Box: actions are float vectors
            if not getattr(policy_cls, "supports_continuous", False):
                raise TypeError(
                    f"{policy_cls.__name__} only supports Discrete "
                    f"action spaces, got {space} — use an algorithm "
                    f"with a continuous policy (e.g. SAC)")
            self._discrete = False
            num_actions = int(np.prod(space.shape))
            self._act_shape = space.shape
            self.config["_continuous"] = True
            self.config["_act_low"] = np.asarray(space.low, np.float32)
            self.config["_act_high"] = np.asarray(space.high, np.float32)
        self.policy = policy_cls(obs_dim, num_actions, self.config)
        self.worker_index = worker_index
        # Connector pipelines (reference: rllib/connectors/) adapt env
        # obs -> policy input and policy action -> env action.
        from ray_tpu.rllib.connectors import get_default_pipelines
        self._obs_pipe, self._act_pipe = get_default_pipelines(
            self.config, action_space=space)
        # Vectorized sampling (reference: env/vector_env.py): one policy
        # forward serves num_envs_per_worker envs per step.
        self._num_envs = int(self.config.get("num_envs_per_worker", 1))
        if self._num_envs > 1:
            from ray_tpu.rllib.env.vector_env import VectorEnv
            self.venv = VectorEnv(
                [self.env] + [env_creator(self.config)
                              for _ in range(self._num_envs - 1)])
            self._vobs = [self._obs_pipe(o) for o in
                          self.venv.vector_reset(seed=self.config["seed"])]
            self._vep_reward = [0.0] * self._num_envs
            self._vep_len = [0] * self._num_envs
        else:
            self.venv = None
            self._obs, _ = self.env.reset(seed=self.config["seed"])
            self._obs = self._obs_pipe(self._obs)
        self._episode_reward = 0.0
        self._episode_len = 0
        self._completed_rewards: List[float] = []
        self._completed_lens: List[int] = []
        # Offline output (reference: rollout config `output` -> offline/
        # json_writer): every sampled fragment is appended as a dataset
        # row usable by BC/MARWIL via input_data=<path>.
        self._output_writer = None
        if self.config.get("output"):
            from ray_tpu.rllib.offline import JsonWriter
            self._output_writer = JsonWriter(self.config["output"])

    def sample(self, num_steps: Optional[int] = None) -> SampleBatch:
        """Collect one fragment of experience with GAE advantages."""
        horizon = num_steps or self.config.get("rollout_fragment_length",
                                               200)
        gamma = self.config.get("gamma", 0.99)
        lam = self.config.get("lambda", 0.95)
        if self.venv is not None:
            return self._sample_vector(horizon, gamma, lam)
        rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                                sb.NEXT_OBS, sb.ACTION_LOGP,
                                sb.VF_PREDS)}
        segments: List[SampleBatch] = []
        seg_start = 0
        for _ in range(horizon):
            action, logp, vf = self.policy.compute_actions(
                self._obs[None, :])
            if self._discrete:
                act_env = int(action[0])
                act_row = act_env
            else:
                act_row = np.asarray(action[0], np.float32)
                act_env = act_row.reshape(self._act_shape)
            if not self._discrete and self._act_pipe.connectors:
                act_env = np.asarray(self._act_pipe(act_env),
                                     np.float32).reshape(self._act_shape)
            obs2, reward, terminated, truncated, _ = self.env.step(
                act_env)
            obs2 = self._obs_pipe(obs2)
            done = terminated or truncated
            rows[sb.OBS].append(self._obs)
            rows[sb.ACTIONS].append(act_row)
            rows[sb.REWARDS].append(float(reward))
            rows[sb.DONES].append(bool(terminated))
            rows[sb.NEXT_OBS].append(obs2)
            rows[sb.ACTION_LOGP].append(float(logp[0]))
            rows[sb.VF_PREDS].append(float(vf[0]))
            self._episode_reward += float(reward)
            self._episode_len += 1
            self._obs = obs2
            if done:
                self._completed_rewards.append(self._episode_reward)
                self._completed_lens.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._obs, _ = self.env.reset()
                self._obs = self._obs_pipe(self._obs)
                # Close the segment at the episode boundary.
                segments.append(self._segment(rows, seg_start,
                                              len(rows[sb.OBS]),
                                              last_value=0.0,
                                              gamma=gamma, lam=lam))
                seg_start = len(rows[sb.OBS])
        if seg_start < len(rows[sb.OBS]):
            # Bootstrap the truncated tail with V(s_T).
            last_v = float(self.policy.value(self._obs[None, :])[0])
            segments.append(self._segment(rows, seg_start,
                                          len(rows[sb.OBS]),
                                          last_value=last_v,
                                          gamma=gamma, lam=lam))
        batch = SampleBatch.concat_samples(segments)
        if self._output_writer is not None:
            self._output_writer.write(batch)
        return batch

    def _sample_vector(self, horizon: int, gamma: float,
                       lam: float) -> SampleBatch:
        """Vectorized fragment: each of the N envs contributes
        horizon // N steps; one batched policy forward per step serves
        all envs (reference: the vector-env sampler path)."""
        n = self._num_envs
        steps = max(1, horizon // n)
        rows = [
            {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS, sb.DONES,
                             sb.NEXT_OBS, sb.ACTION_LOGP, sb.VF_PREDS)}
            for _ in range(n)]
        segments: List[SampleBatch] = []
        seg_start = [0] * n
        for _ in range(steps):
            obs_batch = np.asarray(self._vobs, np.float32)
            actions, logps, vfs = self.policy.compute_actions(obs_batch)
            if self._discrete:
                env_actions = [int(a) for a in actions]
                act_rows = env_actions
            else:
                act_rows = [np.asarray(a, np.float32) for a in actions]
                env_actions = [
                    np.asarray(self._act_pipe(a), np.float32).reshape(
                        self._act_shape) if self._act_pipe.connectors
                    else a.reshape(self._act_shape) for a in act_rows]
            obs2, rews, terms, truncs = self.venv.vector_step(env_actions)
            for i in range(n):
                r = rows[i]
                o2 = self._obs_pipe(obs2[i])
                r[sb.OBS].append(self._vobs[i])
                r[sb.ACTIONS].append(act_rows[i])
                r[sb.REWARDS].append(float(rews[i]))
                r[sb.DONES].append(bool(terms[i]))
                r[sb.NEXT_OBS].append(o2)
                r[sb.ACTION_LOGP].append(float(logps[i]))
                r[sb.VF_PREDS].append(float(vfs[i]))
                self._vep_reward[i] += float(rews[i])
                self._vep_len[i] += 1
                if terms[i] or truncs[i]:
                    self._completed_rewards.append(self._vep_reward[i])
                    self._completed_lens.append(self._vep_len[i])
                    self._vep_reward[i] = 0.0
                    self._vep_len[i] = 0
                    segments.append(self._segment(
                        r, seg_start[i], len(r[sb.OBS]), last_value=0.0,
                        gamma=gamma, lam=lam))
                    seg_start[i] = len(r[sb.OBS])
                    self._vobs[i] = self._obs_pipe(self.venv.reset_at(i))
                else:
                    self._vobs[i] = o2
        for i in range(n):
            if seg_start[i] < len(rows[i][sb.OBS]):
                last_v = float(self.policy.value(
                    np.asarray(self._vobs[i], np.float32)[None, :])[0])
                segments.append(self._segment(
                    rows[i], seg_start[i], len(rows[i][sb.OBS]),
                    last_value=last_v, gamma=gamma, lam=lam))
        batch = SampleBatch.concat_samples(segments)
        if self._output_writer is not None:
            self._output_writer.write(batch)
        return batch

    def _segment(self, rows, start, end, last_value, gamma, lam):
        act_dtype = np.int32 if self._discrete else np.float32
        seg = SampleBatch({
            sb.OBS: np.asarray(rows[sb.OBS][start:end], np.float32),
            sb.ACTIONS: np.asarray(rows[sb.ACTIONS][start:end],
                                   act_dtype),
            sb.REWARDS: np.asarray(rows[sb.REWARDS][start:end], np.float32),
            sb.DONES: np.asarray(rows[sb.DONES][start:end], np.bool_),
            sb.NEXT_OBS: np.asarray(rows[sb.NEXT_OBS][start:end],
                                    np.float32),
            sb.ACTION_LOGP: np.asarray(rows[sb.ACTION_LOGP][start:end],
                                       np.float32),
            sb.VF_PREDS: np.asarray(rows[sb.VF_PREDS][start:end],
                                    np.float32),
        })
        return compute_gae(seg, last_value, gamma, lam)

    def ddppo_epoch(self, num_steps: int, num_sgd_iter: int,
                    minibatch_size: int,
                    group_name: str = "ddppo") -> Dict:
        """One DD-PPO round: sample locally, then SGD with gradients
        allreduced across the worker gang — no central learner
        (reference: rllib/algorithms/ddppo/ddppo.py:91,131, which rides
        torch.distributed; ours rides the framework collective ring).
        Every member runs the same minibatch count, so the allreduce
        rounds stay in lockstep."""
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        from ray_tpu.util import collective

        group = collective.get_group_handle(group_name)
        batch = self.sample(num_steps)
        adv = batch[sb.ADVANTAGES]
        batch[sb.ADVANTAGES] = (
            (adv - adv.mean()) / max(adv.std(), 1e-6)).astype(np.float32)
        rng = np.random.RandomState(self.config["seed"])
        mb = min(minibatch_size, batch.count)
        stats: Dict = {}
        for _ in range(num_sgd_iter):
            shuffled = batch.shuffle(rng)
            for minibatch in shuffled.minibatches(mb):
                grads, stats = self.policy.compute_grads(minibatch)
                flat, unravel = ravel_pytree(grads)
                arr = np.array(flat)  # writable copy (allreduce in-place)
                collective.allreduce(arr, group_name=group_name)
                self.policy.apply_grads(
                    unravel(jnp.asarray(arr / group.world_size)))
        return {"stats": stats, "steps": batch.count}

    @staticmethod
    def _filter_count(state) -> int:
        return sum((s or {}).get("count", 0) for s in (state or []))

    def sample_with_grads(self, num_steps: Optional[int] = None):
        """A3C worker step: sample a fragment and compute the policy
        gradient LOCALLY (reference: a3c's worker-side grad computation);
        returns (grads, count, stats) for async application."""
        import jax
        batch = self.sample(num_steps)
        grads, stats = self.policy.compute_grads(batch)
        return (jax.tree_util.tree_map(np.asarray, grads), batch.count,
                stats)

    def set_weights(self, weights) -> bool:
        # Connector filter statistics ride along (checkpoint restore /
        # cross-worker carry) in a shallow envelope key that MUST be
        # stripped before reaching the policy (whose weights are a raw
        # params pytree).  Applied only when the incoming state has seen
        # MORE data than ours, so a learner broadcast never resets a
        # sampling worker's running estimator.
        state = None
        if isinstance(weights, dict) and "_obs_filters" in weights:
            weights = dict(weights)
            state = weights.pop("_obs_filters")
        self.policy.set_weights(weights)
        if state and self._filter_count(state) > self._filter_count(
                self._obs_pipe.get_state()):
            self._obs_pipe.set_state(state)
        return True

    def get_weights(self):
        w = self.policy.get_weights()
        if isinstance(w, dict):
            w = dict(w)
            w["_obs_filters"] = self._obs_pipe.get_state()
        return w

    def episode_stats(self, clear: bool = True) -> Dict:
        stats = {"episode_rewards": list(self._completed_rewards),
                 "episode_lens": list(self._completed_lens)}
        if clear:
            self._completed_rewards = []
            self._completed_lens = []
        return stats

    def stop(self):
        try:
            self.env.close()
        except Exception:
            pass
        return True
