"""Multi-agent rollout worker: per-agent trajectories routed to policies.

Reference: rllib/evaluation/rollout_worker.py multi-agent path +
episode_v2's per-agent trajectory builders — each agent's experience is
collected under the policy that controlled it (policy_mapping_fn), GAE is
computed per agent-episode with that policy's value head, and sample()
returns a MultiAgentBatch {policy_id: SampleBatch}.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.policy import sample_batch as sb
from ray_tpu.rllib.policy.policy_map import PolicyMap, PolicySpec
from ray_tpu.rllib.policy.sample_batch import SampleBatch, compute_gae


class MultiAgentBatch(dict):
    """policy_id -> SampleBatch (reference: policy/sample_batch.py
    MultiAgentBatch)."""

    @property
    def count(self) -> int:
        return sum(b.count for b in self.values())

    @staticmethod
    def concat_samples(batches: List["MultiAgentBatch"]
                       ) -> "MultiAgentBatch":
        out: Dict[str, List[SampleBatch]] = {}
        for mb in batches:
            for pid, b in mb.items():
                out.setdefault(pid, []).append(b)
        return MultiAgentBatch({
            pid: SampleBatch.concat_samples(bs)
            for pid, bs in out.items()})


class _AgentTrajectory:
    """Accumulates one agent's rows until its episode segment closes."""

    __slots__ = ("rows",)

    def __init__(self):
        self.rows = {k: [] for k in (sb.OBS, sb.ACTIONS, sb.REWARDS,
                                     sb.DONES, sb.NEXT_OBS,
                                     sb.ACTION_LOGP, sb.VF_PREDS)}

    def add(self, obs, action, reward, done, next_obs, logp, vf):
        r = self.rows
        r[sb.OBS].append(obs)
        r[sb.ACTIONS].append(action)
        r[sb.REWARDS].append(float(reward))
        r[sb.DONES].append(bool(done))
        r[sb.NEXT_OBS].append(next_obs)
        r[sb.ACTION_LOGP].append(float(logp))
        r[sb.VF_PREDS].append(float(vf))

    def __len__(self):
        return len(self.rows[sb.OBS])

    def to_segment(self, last_value: float, gamma: float,
                   lam: float) -> SampleBatch:
        r = self.rows
        seg = SampleBatch({
            sb.OBS: np.asarray(r[sb.OBS], np.float32),
            sb.ACTIONS: np.asarray(r[sb.ACTIONS], np.int32),
            sb.REWARDS: np.asarray(r[sb.REWARDS], np.float32),
            sb.DONES: np.asarray(r[sb.DONES], np.bool_),
            sb.NEXT_OBS: np.asarray(r[sb.NEXT_OBS], np.float32),
            sb.ACTION_LOGP: np.asarray(r[sb.ACTION_LOGP], np.float32),
            sb.VF_PREDS: np.asarray(r[sb.VF_PREDS], np.float32),
        })
        return compute_gae(seg, last_value, gamma, lam)


class MultiAgentRolloutWorker:
    def __init__(self, env_creator: Callable, policy_cls, config: Dict,
                 worker_index: int = 0):
        import os
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.config = dict(config)
        self.config["seed"] = self.config.get("seed", 0) + worker_index
        self.env = env_creator(self.config)
        self.mapping_fn = self.config["policy_mapping_fn"]
        specs = {}
        for pid, spec in self.config["policies"].items():
            if isinstance(spec, PolicySpec):
                specs[pid] = spec
            else:  # infer from an agent this policy controls
                agent = spec
                space = self.env.action_space(agent)
                obs_dim = int(np.prod(
                    self.env.observation_space(agent).shape))
                specs[pid] = PolicySpec(obs_dim, int(space.n))
        self.policies = PolicyMap(specs, self.config, policy_cls)
        self.worker_index = worker_index
        self._obs, _ = self.env.reset(seed=self.config["seed"])
        self._traj: Dict[str, _AgentTrajectory] = {}
        self._episode_reward = 0.0
        self._completed_rewards: List[float] = []

    # ------------------------------------------------------------- sampling
    def sample(self, num_steps: Optional[int] = None) -> MultiAgentBatch:
        horizon = num_steps or self.config.get("rollout_fragment_length",
                                               200)
        gamma = self.config.get("gamma", 0.99)
        lam = self.config.get("lambda", 0.95)
        segments: Dict[str, List[SampleBatch]] = {}

        def close(agent_id, last_value):
            traj = self._traj.pop(agent_id, None)
            if traj is None or len(traj) == 0:
                return
            pid = self.mapping_fn(agent_id)
            segments.setdefault(pid, []).append(
                traj.to_segment(last_value, gamma, lam))

        for _ in range(horizon):
            # Group live agents by policy; one batched forward per policy.
            by_policy: Dict[str, List[str]] = {}
            for aid in self._obs:
                by_policy.setdefault(self.mapping_fn(aid), []).append(aid)
            actions, logps, vfs = {}, {}, {}
            for pid, aids in by_policy.items():
                obs = np.asarray([self._obs[a] for a in aids], np.float32)
                a, lp, vf = self.policies[pid].compute_actions(obs)
                for i, aid in enumerate(aids):
                    actions[aid] = int(a[i])
                    logps[aid] = float(lp[i])
                    vfs[aid] = float(vf[i])
            obs2, rews, terms, truncs, _ = self.env.step(actions)
            for aid in actions:
                traj = self._traj.setdefault(aid, _AgentTrajectory())
                terminated = bool(terms.get(aid, False))
                traj.add(self._obs[aid], actions[aid],
                         rews.get(aid, 0.0), terminated,
                         obs2.get(aid, self._obs[aid]),
                         logps[aid], vfs[aid])
                self._episode_reward += float(rews.get(aid, 0.0))
                if terminated or truncs.get(aid, False):
                    close(aid, 0.0)
            if terms.get("__all__") or truncs.get("__all__"):
                for aid in list(self._traj):
                    close(aid, 0.0)
                self._completed_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = obs2
        # Bootstrap still-open trajectories with each policy's V(s).
        for aid in list(self._traj):
            pid = self.mapping_fn(aid)
            if aid in self._obs:
                v = float(self.policies[pid].value(
                    np.asarray(self._obs[aid], np.float32)[None, :])[0])
            else:
                v = 0.0
            close(aid, v)
        return MultiAgentBatch({
            pid: SampleBatch.concat_samples(segs)
            for pid, segs in segments.items()})

    # ------------------------------------------------------------- plumbing
    def get_weights(self):
        return self.policies.get_weights()

    def set_weights(self, weights):
        self.policies.set_weights(weights)

    def episode_stats(self) -> Dict:
        rewards = self._completed_rewards
        self._completed_rewards = []
        return {"episode_rewards": rewards,
                "episode_lens": [0] * len(rewards)}

    def stop(self):
        return True
