"""jax platform management helpers.

The execution environment may pre-register an experimental TPU backend in
every python process (a sitecustomize hook that also forces
``jax_platforms="axon,cpu"`` via jax.config, overriding the JAX_PLATFORMS
env var).  Backend initialization then dials the TPU device tunnel — which
must only ever happen in the one process that owns the chip.  These helpers
pin a process to the intended platform *before* first jax compute.
"""

from __future__ import annotations

import os

_FORCED = {"value": None}


def ensure_cpu(n_devices: int | None = None) -> None:
    """Pin this process's jax to the host CPU platform.  Call before any
    jax compute.  ``n_devices`` forces a virtual multi-device host platform
    (for testing shardings without real chips)."""
    if _FORCED["value"] == ("cpu", n_devices):
        return
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n_devices}"
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = f"{flags} {want}".strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    _FORCED["value"] = ("cpu", n_devices)


def cpu_pinned() -> bool:
    """True when this process's jax is (or will be) on the host CPU
    platform — robust to list values ('cpu,tpu') and casing."""
    plats = [p.strip().lower()
             for p in os.environ.get("JAX_PLATFORMS", "").split(",")]
    return "cpu" in plats or _FORCED["value"] is not None and \
        _FORCED["value"][0] == "cpu"


def enable_cpu_collectives() -> None:
    """Select the gloo cross-process collective transport for CPU gangs
    (jax.distributed federation needs it; on TPU the ICI fabric makes
    it a no-op).  Must run before this process creates its backend
    client; a late call raises inside jax, which we surface as a
    warning because the symptom otherwise appears much later as a
    hanging collective."""
    if not cpu_pinned():
        return
    try:
        import jax
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception as e:
        import logging
        logging.getLogger(__name__).warning(
            "could not select gloo CPU collectives (%r); if this gang "
            "spans processes, cross-process collectives will fail — "
            "was jax already initialized in this worker?", e)


def ensure_accelerator() -> bool:
    """Allow this process to use the real accelerator backend.  Returns True
    if a non-CPU device is visible."""
    try:
        import jax
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            os.environ.pop("JAX_PLATFORMS", None)
        devs = jax.devices()
        return any(d.platform != "cpu" for d in devs)
    except Exception:
        return False


def cpu_mesh_devices(n: int):
    """Return n virtual CPU devices (forcing the host platform count)."""
    ensure_cpu(n)
    import jax
    devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(
            f"asked for {n} virtual cpu devices but jax already initialized "
            f"with {len(devs)}; set XLA_FLAGS=--xla_force_host_platform_"
            f"device_count={n} before the first jax use in this process")
    return devs[:n]
