"""Core-runtime microbenchmarks, mirroring the reference's harness.

Reference: python/ray/_private/ray_perf.py:93 — the numbers recorded in
release/release_logs/1.13.0/microbenchmark.json (BASELINE.md) were made by
this style of loop: time N operations end-to-end through the runtime and
report ops/s.  Run: `python -m ray_tpu._private.ray_perf [--quick]`.
"""

from __future__ import annotations

import json
import os
import statistics
import time

import numpy as np

import ray_tpu

# One static source buffer for the contention probes so the probe cost
# is a copy, not an allocation.
_PROBE_SRC = None


def probe_memcpy_gbps(mb: int = 16, reps: int = 2) -> float:
    """Quick single-thread memcpy probe — the external-contention
    canary.  The cluster is idle between metrics (workers block on
    RPC), so a dip against the suite-start value means SOMETHING ELSE
    is eating the host, not the runtime under test."""
    global _PROBE_SRC
    if _PROBE_SRC is None or len(_PROBE_SRC) != mb << 20:
        _PROBE_SRC = np.random.bytes(mb << 20)
    dest = bytearray(len(_PROBE_SRC))
    mv = memoryview(dest)
    t0 = time.perf_counter()
    for _ in range(reps):
        mv[:] = _PROBE_SRC
    return reps * len(_PROBE_SRC) / (time.perf_counter() - t0) / 1e9


def _pct(sorted_xs, q):
    if not sorted_xs:
        return None
    i = min(len(sorted_xs) - 1, int(round(q * (len(sorted_xs) - 1))))
    return sorted_xs[i]


def timeit(name, fn, multiplier=1, results=None, repeats=3):
    """Time `fn` in `repeats` independent passes and record ALL of
    them plus per-pass load evidence (BENCH r4 lesson: a single pass on
    a contended host can neither confirm nor refute a latency claim).
    Returns the median rate; the full record keeps the best pass, the
    per-invocation latency tail (p50/p95/p99 — a pipelined hot path
    must not buy its median with a worse tail), and the loadavg/memcpy
    context needed to judge whether the host or the runtime was the
    limiter."""
    # Warmup.
    fn()
    memcpy_before = probe_memcpy_gbps()
    rates, loads, lats = [], [], []
    for _ in range(repeats):
        loads.append(round(os.getloadavg()[0], 2))
        start = time.perf_counter()
        count = 0
        prev = start
        while True:
            fn()
            now = time.perf_counter()
            lats.append(now - prev)
            prev = now
            count += 1
            if now - start >= MIN_SECONDS:
                break
        rates.append(count * multiplier / (prev - start))
    med = statistics.median(rates)
    lats.sort()
    print(f"{name}: {med:.2f} /s (best {max(rates):.2f}, "
          f"n={repeats}, load {loads[0]})")
    if results is not None:
        rec = results[name] = {
            "median": round(med, 2),
            "best": round(max(rates), 2),
            "rates": [round(r, 2) for r in rates],
            "load_1m": loads,
            "load_after": round(os.getloadavg()[0], 2),
            "memcpy_probe_gbps": round(memcpy_before, 2),
        }
        # Latency of ONE timed invocation (for multiplier > 1 that is
        # one whole batch/burst, labeled so nobody divides by accident).
        rec["lat_ms"] = {
            "p50": round(1e3 * _pct(lats, 0.50), 3),
            "p95": round(1e3 * _pct(lats, 0.95), 3),
            "p99": round(1e3 * _pct(lats, 0.99), 3),
            "max": round(1e3 * lats[-1], 3),
            "n": len(lats),
            "per": ("call" if multiplier == 1
                    else f"invocation(x{multiplier})"),
        }
    return med


MIN_SECONDS = 2.0
BATCH = 100


@ray_tpu.remote
def noop():
    return None


@ray_tpu.remote
def small(x):
    return x


@ray_tpu.remote
class Actor:
    def noop(self):
        return None


@ray_tpu.remote
class AsyncActor:
    async def noop(self):
        return None


@ray_tpu.remote
class Client:
    """Driver-in-an-actor for n:n scenarios."""

    def __init__(self, peer):
        self.peer = peer

    def batch_calls(self, n):
        # Nested get is the scenario being measured (driver-in-an-actor).
        ray_tpu.get([self.peer.noop.remote() for _ in range(n)],  # noqa: RTL004
                    timeout=120)
        return n

    def batch_tasks(self, n):
        ray_tpu.get([noop.remote() for _ in range(n)], timeout=120)  # noqa: RTL004
        return n


def prefault_store():
    """Touch every page of the local arena so later writes take minor
    faults only.  WARNING: writes zeros through the whole arena — only
    safe while the store is empty (call immediately after init)."""
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker
    if w is None or w.mapping is None:
        return
    if w.raylet is not None:
        # Refuse unless the arena is empty: an already-running session
        # (init(ignore_reinit_error=True) reuse) may hold live objects.
        try:
            used = w._run(w.raylet.request("os_used", {}))["used"]
        except Exception:
            return
        if used:
            print(f"store prefault skipped: {used} bytes in use")
            return
    mv = w.mapping.view
    cap = len(mv)
    zero = bytes(1 << 22)
    t0 = time.perf_counter()
    for off in range(0, cap, len(zero)):
        end = min(off + len(zero), cap)
        mv[off:end] = zero[:end - off]
    print(f"store prefault: {cap >> 20} MB in "
          f"{time.perf_counter() - t0:.1f}s")


def _settle(max_wait: float = 40.0):
    """Wait until the cluster quiesces before timing anything.

    init() prestarts workers whose interpreters import jax (~2s of CPU
    each); on small hosts those imports otherwise bleed into the first
    measurement windows and halve the reported sync-latency floors.
    First wait for the raylet's pool to report no starting workers, then
    probe the noop rate until consecutive bursts agree within 10%."""
    from ray_tpu._private import worker as worker_mod
    deadline = time.perf_counter() + max_wait
    w = worker_mod.global_worker
    if w is not None and w.raylet is not None:
        while time.perf_counter() < deadline:
            try:
                stats = w._run(w.raylet.request("pool_stats", {}))
            except Exception:
                break
            if stats.get("starting", 0) == 0:
                break
            time.sleep(0.3)
    prev = 0.0
    while time.perf_counter() < deadline:
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < 0.25:
            # The serialized round trip IS the measured quantity.
            ray_tpu.get(noop.remote(), timeout=60)  # noqa: RTL001,RTL004
            n += 1
        rate = n / (time.perf_counter() - t0)
        if prev and abs(rate - prev) / max(rate, prev) < 0.10:
            return
        prev = rate
        time.sleep(0.25)


def main(quick: bool = False, only=None):
    """`only`: optional list of substrings — run just the matching
    metrics (and skip their setup cost).  Used by `make bench-quick` to
    probe the hot-path metrics inside a CI time budget."""
    global MIN_SECONDS
    if quick:
        MIN_SECONDS = 0.5

    def sel(name: str) -> bool:
        return only is None or any(s in name for s in only)

    results: dict = {}
    # Host context BEFORE the cluster exists: the pre-init loadavg and
    # memcpy are pure external-contention evidence (nothing of ours is
    # running yet).
    results["_host"] = {
        "cpus": os.cpu_count() or 1,
        "load_pre_init": [round(x, 2) for x in os.getloadavg()],
        "memcpy_pre_init_gbps": round(probe_memcpy_gbps(), 2),
    }
    ray_tpu.init(ignore_reinit_error=True)
    # Pre-fault the arena NOW, while it is guaranteed empty: tmpfs pages
    # are allocated+zeroed on first touch, costing ~4x the copy itself
    # (measured: 0.45 -> 4.6 GB/s put).  Production nodes should do the
    # same at start; the helper scribbles zeros, so it must never run
    # after objects exist.
    prefault_store()
    # A filtered run (make bench-quick) trades some settling for wall
    # clock: it's a regression probe, not the artifact of record.
    _settle(max_wait=10.0 if only else 40.0)

    # --- tasks ----------------------------------------------------------
    if sel("single_client_tasks_sync"):
        timeit("single_client_tasks_sync",
               lambda: ray_tpu.get(noop.remote(), timeout=60), 1, results)
    if sel("single_client_tasks_async"):
        timeit("single_client_tasks_async",
               lambda: ray_tpu.get([noop.remote() for _ in range(BATCH)],
                                   timeout=120), BATCH, results)

    # --- actors ---------------------------------------------------------
    if sel("actor_calls_1_1_sync") or sel("actor_calls_1_1_async"):
        a = Actor.remote()
        ray_tpu.get(a.noop.remote(), timeout=60)
        if sel("actor_calls_1_1_sync"):
            timeit("actor_calls_1_1_sync",
                   lambda: ray_tpu.get(a.noop.remote(), timeout=60),
                   1, results)
        if sel("actor_calls_1_1_async"):
            timeit("actor_calls_1_1_async",
                   lambda: ray_tpu.get(
                       [a.noop.remote() for _ in range(BATCH)],
                       timeout=120), BATCH, results)
    if sel("async_actor_calls_1_1"):
        aa = AsyncActor.remote()
        ray_tpu.get(aa.noop.remote(), timeout=60)
        timeit("async_actor_calls_1_1",
               lambda: ray_tpu.get([aa.noop.remote() for _ in range(BATCH)],
                                   timeout=120), BATCH, results)

    # 1:n — one driver, n actors.
    n = 4
    if sel("actor_calls_1_n_async"):
        actors = [Actor.remote() for _ in range(n)]
        ray_tpu.get([x.noop.remote() for x in actors], timeout=120)
        timeit("actor_calls_1_n_async",
               lambda: ray_tpu.get(
                   [x.noop.remote() for x in actors
                    for _ in range(BATCH // n)],
                   timeout=120), BATCH, results)

    # n:n — n driver-actors each hammering its own peer actor.
    if sel("actor_calls_n_n_async") or sel("multi_client_tasks_async"):
        peers = [Actor.remote() for _ in range(n)]
        clients = [Client.remote(p) for p in peers]
        ray_tpu.get([c.batch_calls.remote(1) for c in clients], timeout=120)
        if sel("actor_calls_n_n_async"):
            timeit("actor_calls_n_n_async",
                   lambda: ray_tpu.get(
                       [c.batch_calls.remote(BATCH) for c in clients],
                       timeout=120), BATCH * n, results)
        if sel("multi_client_tasks_async"):
            timeit("multi_client_tasks_async",
                   lambda: ray_tpu.get(
                       [c.batch_tasks.remote(BATCH) for c in clients],
                       timeout=120), BATCH * n, results)

    # --- lifecycle throughput (BASELINE: 321.7 actors/s, 15.4 PGs/s on
    # a distributed cluster) --------------------------------------------
    def _launch_actors(n=8):
        batch = [Actor.options(num_cpus=0).remote() for _ in range(n)]
        ray_tpu.get([a.noop.remote() for a in batch], timeout=120)
        for a in batch:
            ray_tpu.kill(a)
        return n

    if sel("actor_launch_per_s"):
        timeit("actor_launch_per_s", lambda: _launch_actors(), 8, results)

    def _create_pgs(n=4):
        from ray_tpu.util.placement_group import (placement_group,
                                                  remove_placement_group)
        pgs = [placement_group([{"CPU": 0.01}]) for _ in range(n)]
        for pg in pgs:
            ray_tpu.wait_placement_group_ready(pg, timeout=60)
        for pg in pgs:
            remove_placement_group(pg)
        return n

    if sel("placement_group_per_s"):
        timeit("placement_group_per_s", lambda: _create_pgs(), 4, results)

    # --- object store ---------------------------------------------------
    if sel("put_small_1kb"):
        small_obj = b"x" * 1024
        timeit("put_small_1kb",
               lambda: ray_tpu.put(small_obj), 1, results)
    if sel("put_gigabytes") or sel("get_gigabytes"):
        big = np.random.bytes(100 * 1024 * 1024)  # 100 MB
        if sel("put_gigabytes"):
            timeit("put_gigabytes",
                   lambda: ray_tpu.put(big), 0.1, results)  # GB per put
        if sel("get_gigabytes"):
            big_ref = ray_tpu.put(np.frombuffer(big, dtype=np.uint8))
            timeit("get_gigabytes",
                   lambda: ray_tpu.get(big_ref, timeout=60), 0.1, results)

    ray_tpu.shutdown()
    results["_host"]["load_post_suite"] = [round(x, 2)
                                           for x in os.getloadavg()]
    results["_host"]["memcpy_post_suite_gbps"] = round(
        probe_memcpy_gbps(), 2)
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    import sys
    only = None
    if "--only" in sys.argv:
        only = sys.argv[sys.argv.index("--only") + 1].split(",")
    res = main(quick="--quick" in sys.argv, only=only)
    if "--json-out" in sys.argv:
        path = sys.argv[sys.argv.index("--json-out") + 1]
        with open(path, "w") as f:
            json.dump(res, f)
