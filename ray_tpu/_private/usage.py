"""Usage statistics collection (opt-out, local-first).

Reference: python/ray/_private/usage/usage_lib.py — enabledness
resolved from env var > config file > default, library usages and
extra tags recorded pre- or post-init (buffered, then flushed into
the GCS KV under a usage namespace), and a periodic reporter that
assembles a ``UsageStatsToReport`` snapshot, writes it next to the
session logs, and optionally POSTs it.

Differences by design:

* The reporter NEVER touches the network unless a report URL is
  explicitly configured (``RT_USAGE_STATS_REPORT_URL`` or an injected
  transport) — the reference defaults to its public endpoint; here
  the default sink is only ``<session_dir>/usage_stats.json``.
* Collection is cheap enough to run in the driver (one KV sweep and
  one node-table read per interval); the reference runs it on the
  dashboard head.
"""

from __future__ import annotations

import json
import os
import platform
import threading
import time
from dataclasses import asdict, dataclass, field

from ray_tpu._private import locksan
from enum import Enum, auto
from typing import Callable, Dict, List, Optional

SCHEMA_VERSION = "0.1"
USAGE_NS = "usage_stats"
_LIB_PREFIX = b"library_usage:"
_TAG_PREFIX = b"extra_usage_tag:"

_lock = locksan.make_lock("usage._lock")
_pre_init_libraries: set = set()
_pre_init_tags: Dict[str, str] = {}
_recorded_libraries: set = set()
_reporter: Optional["UsageReporter"] = None

# Injectable transport: callable(url, payload_dict) -> None; raising
# counts the report as failed.  None + no URL => local write only.
_transport: Optional[Callable[[str, dict], None]] = None


class UsageStatsEnabledness(Enum):
    ENABLED_EXPLICITLY = auto()
    DISABLED_EXPLICITLY = auto()
    ENABLED_BY_DEFAULT = auto()


@dataclass
class ClusterStatusToReport:
    total_num_cpus: Optional[int] = None
    total_num_tpus: Optional[int] = None
    total_memory_gb: Optional[float] = None
    total_num_nodes: Optional[int] = None


@dataclass
class UsageStatsToReport:
    """One usage report (reference: usage_lib.py:92 UsageStatsToReport)."""
    schema_version: str
    source: str
    session_id: str
    python_version: str
    os: str
    collect_timestamp_ms: int
    session_start_timestamp_ms: int
    total_num_cpus: Optional[int] = None
    total_num_tpus: Optional[int] = None
    total_memory_gb: Optional[float] = None
    total_num_nodes: Optional[int] = None
    total_num_running_jobs: Optional[int] = None
    library_usages: List[str] = field(default_factory=list)
    extra_usage_tags: Dict[str, str] = field(default_factory=dict)
    total_success: int = 0
    total_failed: int = 0
    seq_number: int = 0


def _config_path() -> str:
    return os.environ.get(
        "RT_USAGE_STATS_CONFIG_PATH",
        os.path.expanduser("~/.ray_tpu/usage_stats.json"))


def usage_stats_enabledness() -> UsageStatsEnabledness:
    """env var > config file > enabled-by-default (reference:
    usage_lib.py:372 _usage_stats_enabledness)."""
    env = os.environ.get("RT_USAGE_STATS_ENABLED")
    if env == "0":
        return UsageStatsEnabledness.DISABLED_EXPLICITLY
    if env == "1":
        return UsageStatsEnabledness.ENABLED_EXPLICITLY
    if env is not None:
        raise ValueError(
            f"RT_USAGE_STATS_ENABLED must be 0 or 1, got {env!r}")
    try:
        with open(_config_path()) as f:
            cfg = json.load(f).get("usage_stats")
    except Exception:
        cfg = None
    if cfg is False:
        return UsageStatsEnabledness.DISABLED_EXPLICITLY
    if cfg is True:
        return UsageStatsEnabledness.ENABLED_EXPLICITLY
    return UsageStatsEnabledness.ENABLED_BY_DEFAULT


def usage_stats_enabled() -> bool:
    """Never raises: record_* call this at library import time, and a
    telemetry env-var typo must not break `import ray_tpu.data` — an
    unparseable value falls back to the default (the explicit `rt
    usage status` path still surfaces the ValueError)."""
    try:
        enabledness = usage_stats_enabledness()
    except ValueError:
        enabledness = UsageStatsEnabledness.ENABLED_BY_DEFAULT
    return enabledness is not UsageStatsEnabledness.DISABLED_EXPLICITLY


def set_usage_stats_enabled_via_config(enabled: bool) -> None:
    """`rt usage enable/disable` (reference: set_usage_stats_enabled_
    via_config — writes the persistent opt-in/out)."""
    path = _config_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    try:
        with open(path) as f:
            cfg = json.load(f)
        if not isinstance(cfg, dict):
            cfg = {}
    except Exception:
        cfg = {}
    cfg["usage_stats"] = enabled
    with open(path, "w") as f:
        json.dump(cfg, f)


def _gcs():
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker
    if w is None or not getattr(w, "connected", False):
        return None
    from ray_tpu._private.gcs_client import GcsClient
    return GcsClient(w)


def _kv_put_nowait(key: bytes, value: bytes) -> bool:
    """Fire-and-forget KV put.  record_* may run during a module
    import ON the CoreWorker's event-loop thread (e.g. an async
    actor's handler importing ray_tpu.serve — the dashboard does
    exactly this), where a synchronous `_run().result()` deadlocks
    the loop on itself.  Telemetry needs no reply, so never wait —
    no synchronous KV path belongs in this module."""
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker
    if w is None or not getattr(w, "connected", False) \
            or getattr(w, "loop", None) is None:
        return False
    try:
        w._call(w._gcs_request(
            "kv_put", {"ns": USAGE_NS, "key": key, "value": value}))
        return True
    except Exception:
        return False


def record_library_usage(library: str) -> None:
    """Mark a library (tune/serve/...) as used this session; buffered
    before init, flushed into the GCS KV afterwards (reference:
    usage_lib.py:300)."""
    with _lock:
        if library in _recorded_libraries:
            return
        _recorded_libraries.add(library)
    if not usage_stats_enabled():
        # Explicitly opted out at collection time: don't even buffer —
        # a later enabled session must not report records the user
        # opted out of.
        return
    if not _kv_put_nowait(_LIB_PREFIX + library.encode(), b"1"):
        with _lock:
            _pre_init_libraries.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    """Record a k/v usage tag (reference: usage_lib.py:266 — the
    reference keys by a TagKey enum; a plain lower_snake string keeps
    the seam open for any library without central registration)."""
    key = key.lower()
    if not usage_stats_enabled():
        return  # opted out at collection time: no buffering either
    if not _kv_put_nowait(_TAG_PREFIX + key.encode(), value.encode()):
        with _lock:
            _pre_init_tags[key] = value


def _flush_pre_init_records() -> None:
    with _lock:
        libs, tags = set(_pre_init_libraries), dict(_pre_init_tags)
        _pre_init_libraries.clear()
        _pre_init_tags.clear()
    for lib in libs:
        _kv_put_nowait(_LIB_PREFIX + lib.encode(), b"1")
    for k, v in tags.items():
        _kv_put_nowait(_TAG_PREFIX + k.encode(), v.encode())


def _as_bytes(x) -> bytes:
    return x if isinstance(x, (bytes, bytearray)) else str(x).encode()


def generate_report(session_id: str,
                    session_start_ms: int,
                    counters: Dict[str, int]) -> UsageStatsToReport:
    """Assemble one report from live cluster state."""
    report = UsageStatsToReport(
        schema_version=SCHEMA_VERSION,
        source=os.environ.get("RT_USAGE_STATS_SOURCE", "OSS"),
        session_id=session_id,
        python_version=platform.python_version(),
        os=platform.system().lower(),
        collect_timestamp_ms=int(time.time() * 1000),
        session_start_timestamp_ms=session_start_ms,
        total_success=counters.get("success", 0),
        total_failed=counters.get("failed", 0),
        seq_number=counters.get("seq", 0),
    )
    gcs = _gcs()
    kv = gcs.kv if gcs is not None else None
    if kv is not None:
        try:
            for key in kv.keys(USAGE_NS, _LIB_PREFIX):
                report.library_usages.append(
                    _as_bytes(key)[len(_LIB_PREFIX):].decode())
            for key in kv.keys(USAGE_NS, _TAG_PREFIX):
                val = kv.get(USAGE_NS, key)
                report.extra_usage_tags[
                    _as_bytes(key)[len(_TAG_PREFIX):].decode()] = (
                        _as_bytes(val).decode() if val is not None else "")
            report.library_usages.sort()
        except Exception:
            pass
    try:
        import ray_tpu
        res = ray_tpu.cluster_resources()
        report.total_num_cpus = int(res.get("CPU", 0))
        report.total_num_tpus = int(res.get("TPU", 0))
        report.total_memory_gb = round(
            res.get("memory", 0) / (1024 ** 3), 2)
        nodes = gcs.nodes.get_all() if gcs is not None else []
        report.total_num_nodes = len(
            [n for n in nodes if n.get("alive")])
        jobs = gcs.jobs.list() if gcs is not None else []
        report.total_num_running_jobs = len(
            [j for j in jobs
             if (j.get("status") or j.get("state")) in ("RUNNING",
                                                        "PENDING")])
    except Exception:
        pass
    return report


class UsageReporter:
    """Periodic report loop (reference: dashboard usage_stats_head.py):
    every interval, write ``usage_stats.json`` beside the session logs
    and POST through the transport when one is configured."""

    def __init__(self, session_dir: str, session_id: str,
                 interval_s: Optional[float] = None):
        self.session_dir = session_dir
        self.session_id = session_id
        self.interval_s = interval_s if interval_s is not None else float(
            os.environ.get("RT_USAGE_STATS_REPORT_INTERVAL_S", "3600"))
        self.report_url = os.environ.get("RT_USAGE_STATS_REPORT_URL", "")
        self._start_ms = int(time.time() * 1000)
        # report_once() is public API AND the loop thread's body: the
        # counters need a real critical section, not loop confinement.
        self._counters_lock = locksan.make_lock(
            "UsageReporter._counters_lock")
        self._counters = {"success": 0, "failed": 0, "seq": 0}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="usage-reporter", daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def report_once(self) -> UsageStatsToReport:
        with self._counters_lock:
            self._counters["seq"] += 1
            counters = dict(self._counters)
        report = generate_report(self.session_id, self._start_ms,
                                 counters)
        error = None
        sent = False
        transport = _transport or (
            _default_transport if self.report_url else None)
        if transport is not None:
            try:
                transport(self.report_url, asdict(report))
                sent = True
                with self._counters_lock:
                    self._counters["success"] += 1
            except Exception as e:
                error = repr(e)
                with self._counters_lock:
                    self._counters["failed"] += 1
        try:
            path = os.path.join(self.session_dir, "usage_stats.json")
            with open(path, "w") as f:
                json.dump({"usage_stats": asdict(report),
                           "success": sent or error is None,
                           "error": error}, f, indent=2)
        except Exception:
            pass
        return report

    def _loop(self):
        # First report soon after startup (reference reports at start
        # then every interval), then steady-state cadence.
        if self._stop.wait(min(10.0, self.interval_s)):
            return
        while True:
            self.report_once()
            if self._stop.wait(self.interval_s):
                return


def _default_transport(url: str, payload: dict) -> None:
    import urllib.request
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=10).read()


def on_init(session_dir: Optional[str], session_id: str) -> None:
    """Driver connected: flush buffered records; start the reporter
    when this driver started the head and stats are enabled."""
    global _reporter
    if not usage_stats_enabled():
        return
    try:
        _flush_pre_init_records()
    except Exception:
        pass
    if session_dir and _reporter is None:
        _reporter = UsageReporter(session_dir, session_id).start()


def on_shutdown() -> None:
    global _reporter
    if _reporter is not None:
        _reporter.stop()
        _reporter = None
    with _lock:
        _recorded_libraries.clear()
