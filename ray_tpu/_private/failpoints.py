"""Deterministic, seeded failpoint registry — the fault-injection plane.

Reference: FoundationDB's simulation testing (Zhou et al., SIGMOD '21)
showed that fault injection is only useful when it is *deterministic and
seeded* — a red run must be replayable — and the ownership paper behind
Ray (Wang et al., NSDI '21) argues recovery must be exercised at the
*message* level, not just by killing whole processes.  This module
supplies both: named failpoints compiled into the runtime's code paths
(protocol frames, transfer chunks, GCS reconnects, heartbeats) plus
connection-level fault rules (partitions, half-open links, slow links)
that the chaos battery drives.

A *failpoint* is a named hook a runtime code path consults::

    if failpoints.ACTIVE:
        act = failpoints.check("transfer.pull_chunk", peer=nid_tag)
        if act is not None and act.kind == "error":
            ...

With nothing configured the cost is one module-attribute truthiness
test — the hot path pays nothing measurable (see the `make bench-quick`
acceptance gate).

Spec grammar (``RT_FAILPOINTS`` env var, :func:`configure`, or the
``set_failpoints`` RPC served by the GCS, raylet, and core worker)::

    specs  ::= spec (";" spec)*
    spec   ::= name "=" action ["(" arg ")"] ("|" mod)*
    action ::= error | delay | drop | dup | disconnect | kill | off
    mod    ::= "p=" FLOAT          probability per eligible hit
             | "hits=" N["-" M]    fire only on hits N..M (1-based)
             | "times=" N          fire at most N times total
             | "peer=" SUBSTR      only when the site's peer matches

Examples::

    RT_FAILPOINTS="protocol.recv=drop|p=0.1"
    RT_FAILPOINTS="transfer.pull_chunk=error|peer=ab12cd34;raylet.heartbeat=delay(500)|hits=3-6"

Named failpoints wired into the runtime:

    ``protocol.send`` / ``protocol.recv``   (peer = connection name)
    ``transfer.pull_chunk`` / ``transfer.push_chunk``  (peer = node tag)
    ``raylet.serve_chunk``                  (peer = serving node tag)
    ``raylet.heartbeat``                    (peer = node tag)
    ``worker.gcs_request``                  (peer = RPC method)
    ``worker.gcs_reconnect``

Determinism: every failpoint owns a hit counter and an RNG stream seeded
from ``(RT_CHAOS_SEED, name)``, so the decision for hit #k of a
failpoint depends only on the seed — never on interleaving with other
failpoints.  Two runs with the same seed and the same per-site call
sequence inject the identical schedule; :data:`LOG` records every
decision so tests can assert it.

Connection rules (partitions / slow links) are separate from named
failpoints: a rule matches connection *names* by substring and installs
per-connection flags (``drop_tx``/``drop_rx``/``delay_tx_s``/
``delay_rx_s``) consulted by the protocol layer.  ``test_utils.py``
builds ``cluster.partition()/heal()/slow_link()`` on top of these.
"""

from __future__ import annotations

import logging
import os
import random
import zlib

logger = logging.getLogger(__name__)

# name -> [Failpoint, ...].  Truthiness of this dict is THE hot-path
# gate: empty means the fault plane is compiled out.
ACTIVE: dict = {}

SEED: int = int(os.environ.get("RT_CHAOS_SEED", "0") or "0")

# Decision log: (name, hit_index, fired, action_kind).  Bounded; reset
# by configure().  The determinism battery asserts two same-seed runs
# produce identical logs.
LOG: list = []
_LOG_CAP = 20000

_ACTIONS = ("error", "delay", "drop", "dup", "disconnect", "kill", "off")


class Action:
    """What a fired failpoint asks the call site to do."""

    __slots__ = ("kind", "arg")

    def __init__(self, kind: str, arg=None):
        self.kind = kind
        self.arg = arg

    def __repr__(self):
        return f"Action({self.kind!r}, {self.arg!r})"

    @property
    def delay_s(self) -> float:
        """delay actions carry milliseconds; convert once here."""
        return float(self.arg or 0.0) / 1000.0


class Failpoint:
    __slots__ = ("name", "action", "prob", "first", "last", "times",
                 "peer", "hits", "fired", "_rng")

    def __init__(self, name: str, action: Action, prob: float = 1.0,
                 first: int = 1, last=None, times=None, peer=None):
        self.name = name
        self.action = action
        self.prob = prob
        self.first = first
        self.last = last
        self.times = times
        self.peer = peer
        self.hits = 0
        self.fired = 0
        self._rng = random.Random()
        self.reseed(SEED)

    def reseed(self, seed: int):
        # Per-failpoint stream: hit #k's probability draw depends only
        # on (seed, name, k), never on other failpoints' draws.
        self._rng.seed(zlib.crc32(self.name.encode()) ^ seed)
        self.hits = 0
        self.fired = 0

    def check(self, peer=None):
        if self.peer is not None and (
                peer is None or self.peer not in str(peer)):
            return None
        self.hits += 1
        h = self.hits
        if h < self.first or (self.last is not None and h > self.last):
            return None
        if self.times is not None and self.fired >= self.times:
            return None
        if self.prob < 1.0 and self._rng.random() >= self.prob:
            _log(self.name, h, False, self.action.kind)
            return None
        self.fired += 1
        _log(self.name, h, True, self.action.kind)
        return self.action

    def describe(self) -> dict:
        return {"name": self.name, "action": self.action.kind,
                "arg": self.action.arg, "prob": self.prob,
                "hits_window": (self.first, self.last),
                "times": self.times, "peer": self.peer,
                "hits": self.hits, "fired": self.fired}


def _log(name, hit, fired, kind):
    if len(LOG) < _LOG_CAP:
        LOG.append((name, hit, fired, kind))


def _parse_one(spec: str) -> Failpoint:
    if "=" not in spec:
        raise ValueError(f"failpoint spec missing '=': {spec!r}")
    name, rest = spec.split("=", 1)
    name = name.strip()
    if not name:
        raise ValueError(f"failpoint spec missing name: {spec!r}")
    parts = [p.strip() for p in rest.split("|")]
    act = parts[0]
    arg = None
    if "(" in act:
        if not act.endswith(")"):
            raise ValueError(f"unbalanced action arg in {spec!r}")
        act, arg = act[:-1].split("(", 1)
    act = act.strip()
    if act not in _ACTIONS:
        raise ValueError(f"unknown failpoint action {act!r} in {spec!r} "
                         f"(expected one of {_ACTIONS})")
    if act == "delay":
        arg = float(arg if arg is not None else 0.0)
    prob, first, last, times, peer = 1.0, 1, None, None, None
    for mod in parts[1:]:
        if not mod:
            continue
        if mod.startswith("p="):
            prob = float(mod[2:])
        elif mod.startswith("hits="):
            win = mod[5:]
            if "-" in win:
                a, b = win.split("-", 1)
                first, last = int(a), int(b)
            else:
                first = last = int(win)
        elif mod.startswith("times="):
            times = int(mod[6:])
        elif mod.startswith("peer="):
            peer = mod[5:]
        else:
            raise ValueError(f"unknown failpoint modifier {mod!r} "
                             f"in {spec!r}")
    return Failpoint(name, Action(act, arg), prob=prob, first=first,
                     last=last, times=times, peer=peer)


def parse(specs: str) -> list:
    return [_parse_one(s) for s in (specs or "").split(";") if s.strip()]


def configure(specs: str, seed=None) -> dict:
    """Replace the active failpoint set (empty string clears it) and
    reset counters + the decision log.  ``seed`` overrides the global
    chaos seed for the new set."""
    global SEED
    if seed is not None:
        SEED = int(seed)
    table: dict = {}
    for fp in parse(specs):
        if fp.action.kind == "off":
            continue
        fp.reseed(SEED)
        table.setdefault(fp.name, []).append(fp)
    ACTIVE.clear()
    ACTIVE.update(table)
    del LOG[:]
    if table:
        logger.info("failpoints active (seed=%d): %s", SEED,
                    "; ".join(sorted(table)))
    return table


def set_failpoint(spec: str):
    """Add/replace ONE failpoint (by name) without disturbing others."""
    fp = _parse_one(spec)
    if fp.action.kind == "off":
        ACTIVE.pop(fp.name, None)
        return None
    fp.reseed(SEED)
    ACTIVE[fp.name] = [fp]
    return fp


def clear(name=None):
    if name is None:
        ACTIVE.clear()
    else:
        ACTIVE.pop(name, None)


def check(name: str, peer=None):
    """Consult failpoint ``name``; returns the Action to apply or None.
    Call sites guard with ``if failpoints.ACTIVE:`` first."""
    fps = ACTIVE.get(name)
    if not fps:
        return None
    for fp in fps:
        act = fp.check(peer)
        if act is not None:
            return act
    return None


def describe() -> list:
    return [fp.describe() for fps in ACTIVE.values() for fp in fps]


def apply_rpc(body: dict) -> dict:
    """Handler body for the ``set_failpoints`` RPC served by the GCS,
    raylet, and core worker — tests flip faults on a LIVE process
    mid-run.  Accepted keys (all optional):

        specs      full replacement spec string ("" clears everything)
        add        one spec added/replaced without disturbing the rest
        seed       new chaos seed (with specs: applied to the new set)
        conn_rules [[match_substrings, flags], ...] partition/slow-link
                   rules (replaces the rule set; [] heals)

    Returns the live state so tests can assert what's armed."""
    body = body or {}
    if body.get("specs") is not None:
        configure(body["specs"], seed=body.get("seed"))
    elif body.get("seed") is not None:
        global SEED
        SEED = int(body["seed"])
        for fps in ACTIVE.values():
            for fp in fps:
                fp.reseed(SEED)
        del LOG[:]
    if body.get("add"):
        set_failpoint(body["add"])
    if body.get("conn_rules") is not None:
        set_conn_rules(body["conn_rules"])
    return {"ok": True, "seed": SEED, "active": describe(),
            "conn_rules": [[list(m), dict(f)] for m, f in CONN_RULES],
            "log_len": len(LOG)}


# ------------------------------------------------------- connection rules
# Partition / slow-link flags matched against Connection names.  A rule
# is (match, flags): every substring in ``match`` must appear in the
# connection's name.  Flags merge across matching rules.

class ConnFault:
    __slots__ = ("drop_tx", "drop_rx", "delay_tx_s", "delay_rx_s")

    def __init__(self, drop_tx=False, drop_rx=False,
                 delay_tx_s=0.0, delay_rx_s=0.0):
        self.drop_tx = drop_tx
        self.drop_rx = drop_rx
        self.delay_tx_s = delay_tx_s
        self.delay_rx_s = delay_rx_s

    def __repr__(self):
        return (f"ConnFault(drop_tx={self.drop_tx}, drop_rx={self.drop_rx},"
                f" delay_tx_s={self.delay_tx_s},"
                f" delay_rx_s={self.delay_rx_s})")


CONN_RULES: list = []  # [(match: tuple[str, ...], flags: dict), ...]


def conn_fault_for(name: str):
    """Merged ConnFault for a connection name, or None."""
    if not CONN_RULES:
        return None
    flags: dict = {}
    for match, f in CONN_RULES:
        if all(m in name for m in match):
            for k, v in f.items():
                if isinstance(v, bool):
                    flags[k] = flags.get(k, False) or v
                else:
                    flags[k] = max(flags.get(k, 0.0), float(v))
    if not any(flags.values()):
        return None
    return ConnFault(**flags)


def set_conn_rules(rules):
    """Replace the connection-rule set and re-resolve the fault flags of
    every LIVE connection in this process (new connections resolve at
    creation).  Loop-thread callers only touch attribute assignment, so
    cross-thread use from test helpers is safe."""
    CONN_RULES[:] = [(tuple(m), dict(f)) for m, f in rules]
    _sweep_live_conns()


def add_conn_rule(match, **flags):
    CONN_RULES.append((tuple(match), dict(flags)))
    _sweep_live_conns()


def clear_conn_rules():
    del CONN_RULES[:]
    _sweep_live_conns()


def _sweep_live_conns():
    # Late import: protocol imports this module at load time.
    try:
        from ray_tpu._private import protocol
    except Exception:  # pragma: no cover - import cycle during teardown
        return
    for conn in list(protocol._LIVE_CONNS):
        try:
            conn._fault = conn_fault_for(conn.name)
        except Exception:
            pass


# Env activation: a process started with RT_FAILPOINTS in its
# environment arms the plane at import (workers inherit the env from
# their raylet, so one env var arms a whole node).
_env_spec = os.environ.get("RT_FAILPOINTS")
if _env_spec:
    try:
        configure(_env_spec)
    except ValueError as e:  # pragma: no cover - operator typo
        logger.error("ignoring malformed RT_FAILPOINTS: %s", e)
