"""Composable scheduling policies over an incrementally indexed cluster view.

Reference: the raylet's pluggable scheduling policies
(src/ray/raylet/scheduling/policy/ — hybrid_scheduling_policy.h:48,
spread_scheduling_policy, node_affinity) and the cluster resource
manager they score against (cluster_resource_manager.h), which keeps
per-node views updated from resource-usage broadcasts instead of
rescanning the world per decision.

Two layers:

* ``ScanPolicy`` — a filter chain plus an optional scorer evaluated by
  a full scan in node-registration order.  This is the DEFINITIONAL
  semantics (bit-compatible with the legacy inline ``_pick_*`` loops in
  raylet.py: earliest-registered strictly-smallest score wins), kept as
  the parity reference and as the ``cfg.sched_indexed_view=False``
  escape hatch.

* ``ClusterIndex`` — the incremental twin.  For every resource shape a
  decision has asked about it maintains

    - ``total_fits``: node-ids whose TOTAL capacity can ever hold the
      shape (changes only on membership / capacity change),
    - a hybrid-score min-heap and a load min-heap of ``(score, seq,
      node_id, ver)`` entries, pushed whenever a node delta leaves the
      shape available-feasible on that node.

  Entries are validated lazily at pick time: an entry is live iff the
  node still exists and its version matches, and a live entry's score
  is by construction current (scores derive only from versioned state).
  A pick therefore pops only entries invalidated since the last pick —
  amortized O(log n) per node delta and O(1) per decision, instead of a
  full O(nodes) rescan per lease request.  Because the heaps order by
  ``(score, seq)`` and a stale entry can never shadow a live one, the
  indexed pick returns exactly the ScanPolicy answer.

``SchedulingPolicies`` is the facade the raylet holds: feed it node
views/deltas, ask it for spillback / hybrid / spread targets.
"""

from __future__ import annotations

import heapq
from collections import OrderedDict

from ray_tpu._private.config import GLOBAL_CONFIG as cfg


def shape_key(resources: dict | None) -> tuple:
    """Canonical hashable key for a resource demand shape."""
    return tuple(sorted((k, float(v))
                        for k, v in (resources or {}).items() if v))


def fits(pool: dict, shape: tuple) -> bool:
    return all(pool.get(k, 0) >= v for k, v in shape)


_UNSET = object()  # "field not present in this delta" marker


def hybrid_score(entry: "NodeEntry", shape_map: dict) -> float:
    """Critical-resource utilization after placing the request, plus a
    small backlog tiebreak — identical arithmetic to the legacy
    ``_pick_hybrid_target`` so indexed and scan picks agree bitwise."""
    score = 0.0
    avail = entry.avail
    for k, cap in entry.total.items():
        if cap <= 0:
            continue
        used = cap - avail.get(k, 0) + shape_map.get(k, 0)
        s = used / cap
        if s > score:
            score = s
    return score + 0.01 * entry.load


class NodeEntry:
    __slots__ = ("node_id", "addr", "total", "avail", "load",
                 "draining", "reserved", "seq", "ver")

    def __init__(self, node_id, seq):
        self.node_id = node_id
        self.addr = None
        self.total: dict = {}
        self.avail: dict = {}
        self.load = 0
        self.draining = False
        # Autopilot reservation (beneficiary workload id or None): a
        # reserved node drains its current leases instead of taking new
        # ones — same treatment as draining in every filter/index path.
        self.reserved = None
        self.seq = seq   # registration order (legacy iteration order)
        self.ver = 0     # bumped on every state change


# --------------------------------------------------------------- filters
# A filter is ``f(ctx, entry) -> bool``; chains are plain tuples so a
# policy is data, not a subclass forest.

def not_excluded(ctx, e):
    return e.node_id != ctx.exclude


def not_draining(ctx, e):
    return not e.draining


def not_reserved(ctx, e):
    return e.reserved is None


def fits_total(ctx, e):
    return fits(e.total, ctx.shape)


def fits_avail(ctx, e):
    return fits(e.avail, ctx.shape)


class PolicyContext:
    __slots__ = ("shape", "shape_map", "exclude", "bound")

    def __init__(self, resources, exclude=None, bound=None):
        self.shape = shape_key(resources)
        self.shape_map = dict(self.shape)
        self.exclude = exclude
        # Initial score bound: a candidate must score strictly below it
        # (spread seeds this with the local load).
        self.bound = bound


class ScanPolicy:
    """Full-scan reference policy: apply the filter chain in node
    registration order; with a scorer, the earliest strictly-smallest
    scoring node wins (legacy semantics), else first admitted wins."""

    def __init__(self, filters, scorer=None):
        self.filters = tuple(filters)
        self.scorer = scorer

    def pick(self, entries, ctx: PolicyContext):
        best = None
        best_score = ctx.bound
        for e in entries:
            if not all(f(ctx, e) for f in self.filters):
                continue
            if self.scorer is None:
                return e
            s = self.scorer(e, ctx.shape_map)
            if best_score is None or s < best_score:
                best, best_score = e, s
        return best


HYBRID_POLICY = ScanPolicy(
    (not_excluded, not_draining, not_reserved, fits_avail),
    scorer=hybrid_score)
SPREAD_POLICY = ScanPolicy(
    (not_excluded, not_draining, not_reserved, fits_avail),
    scorer=lambda e, shape_map: e.load)
# Legacy spillback admitted any total-fitting node; the chain adds the
# dead/draining skip (the raylet's index never holds dead nodes) and
# selection is rotated by SchedulingPolicies.pick_spillback below.
SPILLBACK_FILTERS = (not_excluded, not_draining, not_reserved,
                     fits_total)


class _ShapeIndex:
    __slots__ = ("shape", "shape_map", "total_fits", "hyb", "spr",
                 "rotation", "_order")

    def __init__(self, shape):
        self.shape = shape
        self.shape_map = dict(shape)
        self.total_fits: dict = {}   # node_id -> seq
        self.hyb: list = []          # (score, seq, ver, node_id)
        self.spr: list = []          # (load,  seq, ver, node_id)
        self.rotation = 0            # spillback round-robin cursor
        self._order = None           # cached seq-sorted total_fits ids

    def order(self):
        if self._order is None:
            self._order = tuple(sorted(self.total_fits,
                                       key=self.total_fits.get))
        return self._order


class ClusterIndex:
    """Incrementally-maintained per-shape candidate sets and score heaps
    over the remote-node views (see module docstring)."""

    MAX_SHAPES = 128

    def __init__(self):
        self.nodes: dict = {}               # node_id -> NodeEntry
        self._shapes: OrderedDict = OrderedDict()  # LRU of _ShapeIndex
        self._seq = 0
        # Globally monotonic version stamps: a node that de-registers
        # and comes back must never reuse a version, or a stale heap
        # entry from its previous life could validate against it.
        self._ver = 0
        self.stats = {"updates": 0, "picks": 0, "scanned": 0,
                      "heap_pushes": 0, "rebuilds": 0}

    # ------------------------------------------------------------ feeding
    def upsert(self, view: dict):
        """Full node view (registration / re-seed after reconnect)."""
        nid = view["node_id"]
        e = self.nodes.get(nid)
        if e is None:
            e = NodeEntry(nid, self._seq)
            self._seq += 1
            self.nodes[nid] = e
        e.addr = tuple(view["addr"])
        e.total = dict(view.get("resources") or {})
        e.avail = dict(view.get("available") or e.total)
        e.load = view.get("load", 0)
        e.draining = bool(view.get("draining", False))
        e.reserved = view.get("reserved")
        self._ver += 1
        e.ver = self._ver
        self._reindex(e, membership=True)

    def update(self, nid, available=None, load=None, draining=None,
               reserved=_UNSET):
        """Heartbeat-delta update: only what changed travels."""
        e = self.nodes.get(nid)
        if e is None:
            return False
        if available is not None:
            e.avail = dict(available)
        if load is not None:
            e.load = load
        if draining is not None:
            e.draining = bool(draining)
        if reserved is not _UNSET:
            # None is a meaningful value here (reservation cleared), so
            # the no-change default is the module sentinel.
            e.reserved = reserved
        self._ver += 1
        e.ver = self._ver
        self._reindex(e, membership=False)
        return True

    def remove(self, nid):
        e = self.nodes.pop(nid, None)
        if e is None:
            return
        for si in self._shapes.values():
            if si.total_fits.pop(nid, None) is not None:
                si._order = None
        # Heap entries die lazily (node lookup misses at pick time).

    def entries(self):
        """Registration-order iteration (dict insertion order == seq
        order; removals don't disturb it) — the scan path's input."""
        return self.nodes.values()

    # ----------------------------------------------------------- indexing
    def _reindex(self, e, membership):
        self.stats["updates"] += 1
        for si in self._shapes.values():
            self._index_into(si, e, membership)

    def _index_into(self, si: _ShapeIndex, e: NodeEntry, membership):
        if membership:
            if fits(e.total, si.shape):
                if e.node_id not in si.total_fits:
                    si.total_fits[e.node_id] = e.seq
                    si._order = None
            elif si.total_fits.pop(e.node_id, None) is not None:
                si._order = None
        if not e.draining and e.reserved is None \
                and fits(e.avail, si.shape):
            # ver (globally unique) breaks (score, seq) ties so the
            # comparison never reaches the node-id payload.
            heapq.heappush(si.hyb, (hybrid_score(e, si.shape_map),
                                    e.seq, e.ver, e.node_id))
            heapq.heappush(si.spr, (e.load, e.seq, e.ver, e.node_id))
            self.stats["heap_pushes"] += 2
            if len(si.hyb) > max(64, 4 * len(self.nodes)):
                self._rebuild(si)

    def _rebuild(self, si: _ShapeIndex):
        """Compact a heap bloated by stale entries (bounded amortized
        cost: triggered once per O(nodes) pushes)."""
        self.stats["rebuilds"] += 1
        si.hyb = [(hybrid_score(e, si.shape_map), e.seq, e.ver, e.node_id)
                  for e in self.nodes.values()
                  if not e.draining and e.reserved is None
                  and fits(e.avail, si.shape)]
        heapq.heapify(si.hyb)
        si.spr = [(e.load, e.seq, e.ver, e.node_id)
                  for e in self.nodes.values()
                  if not e.draining and e.reserved is None
                  and fits(e.avail, si.shape)]
        heapq.heapify(si.spr)

    def shape_index(self, resources) -> _ShapeIndex:
        key = shape_key(resources)
        si = self._shapes.get(key)
        if si is None:
            si = _ShapeIndex(key)
            self._shapes[key] = si
            for e in self.nodes.values():
                self._index_into(si, e, membership=True)
            while len(self._shapes) > self.MAX_SHAPES:
                self._shapes.popitem(last=False)
        else:
            self._shapes.move_to_end(key)
        return si

    # -------------------------------------------------------------- picks
    def _pop_best(self, heap, exclude, bound=None):
        """Smallest live heap entry (strictly below ``bound`` if given).
        Stale entries (version mismatch / departed node) are discarded;
        a live entry for the excluded node is held out and re-pushed —
        at most one live entry per node exists (one push per version)."""
        self.stats["picks"] += 1
        held = None
        best = None
        while heap:
            score, seq, ver, nid = heap[0]
            self.stats["scanned"] += 1
            e = self.nodes.get(nid)
            if e is None or e.ver != ver:
                heapq.heappop(heap)
                continue
            if nid == exclude:
                held = heapq.heappop(heap)
                continue
            if bound is None or score < bound:
                best = e
            break
        if held is not None:
            heapq.heappush(heap, held)
        return best

    def pick_hybrid(self, resources, exclude=None):
        return self._pop_best(self.shape_index(resources).hyb, exclude)

    def pick_spread(self, resources, bound, exclude=None):
        return self._pop_best(self.shape_index(resources).spr, exclude,
                              bound=bound)

    def pick_spillback(self, resources, exclude=None):
        """Rotate among nodes that can EVER hold the shape, preferring
        one where it fits right now — so a burst of infeasible-locally
        requests fans across eligible targets instead of piling onto
        the first node in view order (and never lands on a draining
        node)."""
        si = self.shape_index(resources)
        order = si.order()
        n = len(order)
        if not n:
            return None
        self.stats["picks"] += 1
        start = si.rotation % n
        chosen = None
        fallback = None
        for i in range(n):
            nid = order[(start + i) % n]
            e = self.nodes.get(nid)
            self.stats["scanned"] += 1
            if e is None or e.node_id == exclude or e.draining \
                    or e.reserved is not None:
                continue
            if fallback is None:
                fallback = (e, i)
            if fits(e.avail, si.shape):
                chosen = (e, i)
                break
        e, i = chosen or fallback or (None, 0)
        if e is not None:
            si.rotation = (start + i + 1) % n
        return e


class SchedulingPolicies:
    """The raylet's spillback / spread / hybrid decisions.  Holds one
    ClusterIndex fed from GCS node events; ``use_index=False`` (or
    cfg.sched_indexed_view=False) routes picks through the full-scan
    reference policies over the same entries instead."""

    def __init__(self, index: ClusterIndex | None = None, use_index=None):
        self.index = index or ClusterIndex()
        self._use_index = use_index
        # Scan-mode spillback rotation cursors (shape -> position), so
        # the escape hatch keeps the rotate-among-eligible semantics
        # without touching the index's shape tables.
        self._scan_rotation: dict = {}

    def _indexed(self) -> bool:
        if self._use_index is not None:
            return self._use_index
        return cfg.sched_indexed_view

    @staticmethod
    def _addr(e):
        return tuple(e.addr) if e is not None else None

    def pick_hybrid(self, resources, exclude=None):
        if self._indexed():
            return self._addr(self.index.pick_hybrid(resources, exclude))
        ctx = PolicyContext(resources, exclude=exclude)
        return self._addr(HYBRID_POLICY.pick(self.index.entries(), ctx))

    def pick_spread(self, resources, local_load, exclude=None):
        if self._indexed():
            return self._addr(self.index.pick_spread(
                resources, bound=local_load, exclude=exclude))
        ctx = PolicyContext(resources, exclude=exclude, bound=local_load)
        return self._addr(SPREAD_POLICY.pick(self.index.entries(), ctx))

    def pick_spillback(self, resources, exclude=None):
        if self._indexed():
            return self._addr(self.index.pick_spillback(resources,
                                                        exclude))
        # Full-scan reference path: same eligibility chain (skip
        # excluded/draining, total must fit) and the same contract —
        # prefer a target where the shape fits NOW, rotate among
        # eligible — evaluated by one pass in registration order.
        ctx = PolicyContext(resources, exclude=exclude)
        eligible = [e for e in self.index.entries()
                    if all(f(ctx, e) for f in SPILLBACK_FILTERS)]
        if not eligible:
            return None
        start = self._scan_rotation.get(ctx.shape, 0) % len(eligible)
        order = eligible[start:] + eligible[:start]
        chosen = next((e for e in order if fits(e.avail, ctx.shape)),
                      order[0])
        self._scan_rotation[ctx.shape] = \
            (start + order.index(chosen) + 1) % len(eligible)
        return self._addr(chosen)
