"""Value serialization with zero-copy buffer support.

TPU-native equivalent of the reference's serialization layer (reference:
python/ray/_private/serialization.py:89 SerializationContext — cloudpickle
plus pickle-protocol-5 out-of-band buffers so large numpy/arrow payloads are
written into / read from plasma without copies).

Wire format of a serialized object:

  [u32 meta_len][pickled payload][buf0][buf1]...

where the pickled payload was produced with a ``buffer_callback`` so every
PickleBuffer (numpy arrays, bytes-like) is stored out-of-band.  ``meta``
pickles the (nested_refs, buffer_lengths) pair.  Deserialization re-creates
the buffers as zero-copy memoryviews over the source buffer (shared-memory
segment or socket bytes).

jax.Array values are device-fetched to numpy on serialize (host transfer is
explicit — HBM->host traffic is the scarce resource on TPU, reference GPU
code relies on implicit .cpu() in torch pickling instead).
"""

from __future__ import annotations

import pickle
import struct
from dataclasses import dataclass

import cloudpickle

from ray_tpu._private.object_ref import ObjectRef, track_nested_refs

_U32 = struct.Struct("<I")
_PROTO = 5


@dataclass
class SerializedObject:
    meta: bytes          # pickled (nested_ref_states, [len(buf), ...])
    inband: bytes        # pickle-5 stream with out-of-band buffers
    buffers: list        # list of buffer-protocol objects

    def total_size(self) -> int:
        return _U32.size + _U32.size + len(self.meta) + len(self.inband) + sum(
            len(memoryview(b).cast("B")) for b in self.buffers)

    def write_into(self, dest: memoryview) -> int:
        off = 0
        dest[off:off + _U32.size] = _U32.pack(len(self.meta)); off += _U32.size
        dest[off:off + _U32.size] = _U32.pack(len(self.inband)); off += _U32.size
        dest[off:off + len(self.meta)] = self.meta; off += len(self.meta)
        dest[off:off + len(self.inband)] = self.inband; off += len(self.inband)
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            dest[off:off + len(mv)] = mv
            off += len(mv)
        return off

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size())
        self.write_into(memoryview(out))
        return bytes(out)


def _convert_jax_arrays(value):
    """No-op hook; jax.Arrays pickle via numpy conversion already."""
    return value


_OOB_BYTES_MIN = 64 * 1024


class _OOBBytes:
    """Carrier that moves a large top-level bytes/bytearray payload through
    pickle-5 OUT-OF-BAND instead of copying it into the in-band stream.
    pickle only externalizes PickleBuffer objects, and plain bytes are
    always serialized in-band — so a 100MB `put(b"...")` would otherwise
    cost two extra copies (stream assembly + stream→shm).  Unpickling
    reconstructs the original type directly; the wrapper never survives."""

    __slots__ = ("ctor", "payload")

    def __init__(self, ctor, payload):
        self.ctor = ctor          # bytes or bytearray
        self.payload = payload

    def __reduce_ex__(self, protocol):
        return (self.ctor, (pickle.PickleBuffer(self.payload),))


class _Pickler(pickle.Pickler):
    """Plain pickle, except objects DEFINED in the driver script's
    ``__main__`` (functions, classes, their instances' classes) ship
    by value as a nested cloudpickle blob: plain pickle would encode
    them as a reference to ``__main__``, which no worker can resolve
    (its __main__ is worker_main).  Handled inline in ONE pass —
    payloads embedding driver-defined callables are the steady state
    for graph schedulers (dask-on-ray), so a full dump-then-redo
    fallback would double every submit's serialization cost.

    Primitive containers and buffer-protocol data never reach
    ``reducer_override`` (the C pickler's dedicated save paths run
    first), so the data hot path is unaffected."""

    def reducer_override(self, obj):
        try:
            if ((isinstance(obj, type) or callable(obj))
                    and getattr(obj, "__module__", None) == "__main__"):
                return (cloudpickle.loads, (cloudpickle.dumps(obj),))
        except Exception:
            pass
        return NotImplemented


def _pickle_dumps(target, buffer_callback) -> bytes:
    import io
    f = io.BytesIO()
    _Pickler(f, _PROTO, buffer_callback=buffer_callback).dump(target)
    return f.getvalue()


def serialize(value) -> tuple[SerializedObject, list[ObjectRef]]:
    """Serialize ``value``; returns the payload and any ObjectRefs nested in it."""
    buffers: list = []
    target = value
    if type(value) in (bytes, bytearray) and len(value) >= _OOB_BYTES_MIN:
        target = _OOBBytes(type(value), value)
    with track_nested_refs() as nested:
        try:
            # _Pickler intercepts every function/class/callable save,
            # which covers all paths that would emit a __main__ global
            # reference (the one residual escape — a legacy __reduce__
            # returning a bare attribute-name string — surfaces as a
            # clear AttributeError on the worker).
            inband = _pickle_dumps(target, buffers.append)
        except Exception:
            buffers.clear()
            nested.clear()  # refs tracked during the failed attempt
            inband = cloudpickle.dumps(target, protocol=_PROTO,
                                       buffer_callback=buffers.append)
    raw_bufs = [b.raw() for b in buffers]
    ref_states = [(r.id, r.owner_addr) for r in nested]
    meta = pickle.dumps((ref_states, [len(memoryview(b).cast("B")) for b in raw_bufs]))
    return SerializedObject(meta, inband, raw_bufs), list(nested)


def deserialize(data) -> object:
    """Deserialize from a bytes-like; buffers alias ``data`` (zero copy)."""
    mv = memoryview(data).cast("B")
    meta_len = _U32.unpack_from(mv, 0)[0]
    inband_len = _U32.unpack_from(mv, _U32.size)[0]
    off = 2 * _U32.size
    meta = bytes(mv[off:off + meta_len]); off += meta_len
    inband = mv[off:off + inband_len]; off += inband_len
    _ref_states, buf_lens = pickle.loads(meta)
    bufs = []
    for blen in buf_lens:
        bufs.append(pickle.PickleBuffer(mv[off:off + blen]))
        off += blen
    return pickle.loads(inband, buffers=bufs)


def nested_refs_of(data) -> list[tuple]:
    """Read just the nested-ref states from a serialized blob (no full load)."""
    mv = memoryview(data).cast("B")
    meta_len = _U32.unpack_from(mv, 0)[0]
    meta = bytes(mv[2 * _U32.size:2 * _U32.size + meta_len])
    ref_states, _ = pickle.loads(meta)
    return ref_states


def dumps_function(fn) -> bytes:
    """Pickle a function/class plus the exporting process's sys.path.

    cloudpickle serializes importable-module globals *by reference*; a
    worker process can only resolve those if the defining module is on its
    own sys.path. Drivers often have extra entries (pytest inserts the
    test dir, scripts insert their own dir), so we ship the path list and
    replay missing entries worker-side before unpickling (reference keeps
    environments identical instead: python/ray/_private/function_manager.py).
    """
    import sys
    payload = {"pickle": cloudpickle.dumps(fn),
               "sys_path": [p for p in sys.path if p]}
    return pickle.dumps(payload)


def loads_function(data):
    import os
    import sys
    payload = pickle.loads(data)
    for p in payload.get("sys_path") or []:
        if p not in sys.path and os.path.isdir(p):
            sys.path.append(p)
    return cloudpickle.loads(payload["pickle"])
