"""Serve data-plane microbenchmarks.

Reference: python/ray/serve/benchmarks/microbenchmark.py — the
reference measures handle-call throughput (sync + batch) and HTTP
proxy requests/s on a noop deployment; its release suites
(release/release_tests.yaml serve entries) track the same two planes.
This harness mirrors that shape and adds the DIRECT actor-call rate of
the same runtime so the artifact separates "Serve layer overhead" from
"runtime floor": handle calls ride the router + replica scheduler on
top of plain actor calls, HTTP adds the aiohttp proxy hop.

Run: `python -m ray_tpu._private.serve_perf [--json-out PATH] [--probe]`.
`--probe` is the <60 s hot-path regression probe used by
`make bench-quick`: direct actor call + serve handle call + the serve
overhead decomposition, skipping the HTTP plane.
"""

from __future__ import annotations

import json
import os

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import ray_perf
from ray_tpu._private.ray_perf import timeit as _timeit

BATCH = 50
ray_perf.MIN_SECONDS = 0.5


def main(probe: bool = False) -> dict:
    if probe:
        ray_perf.MIN_SECONDS = 0.4
    results: dict = {}
    results["_host"] = {"cpus": os.cpu_count() or 1,
                        "load_pre_init": [round(x, 2)
                                          for x in os.getloadavg()]}
    ray_tpu.init(ignore_reinit_error=True)

    # Runtime floor: a plain actor call through the same core runtime.
    @ray_tpu.remote
    class Direct:
        def noop(self, _=None):
            return b"ok"

    d = Direct.remote()
    ray_tpu.get(d.noop.remote(), timeout=60)
    _timeit("direct_actor_calls_per_s",
            lambda: ray_tpu.get(d.noop.remote(), timeout=60),
            1, results=results)
    if not probe:
        _timeit("direct_actor_batch_per_s",
                lambda: ray_tpu.get([d.noop.remote() for _ in range(BATCH)],
                                    timeout=120), BATCH, results=results)

    # Serve handle plane: router + replica scheduler on top.
    @serve.deployment(name="noop")
    def noop(req):
        return b"ok"

    serve.start(_start_proxy=not probe,
                http_options={"host": "127.0.0.1", "port": 0,
                              "access_log": False})
    handle = noop.deploy()
    handle.remote(None).result(timeout=60)
    _timeit("serve_handle_calls_per_s",
            lambda: handle.remote(None).result(timeout=60),
            1, results=results)

    if probe:
        # Overhead decomposition only (the probe's whole point): a
        # handle-call regression shows up here before a full bench run.
        floor = results["direct_actor_calls_per_s"]["median"]
        hnd = results["serve_handle_calls_per_s"]["median"]
        results["_overhead_ms"] = {
            "direct_actor_call": round(1e3 / floor, 3),
            "handle_call": round(1e3 / hnd, 3),
            "serve_layer_added": round(1e3 / hnd - 1e3 / floor, 3),
        }
        serve.shutdown()
        ray_tpu.shutdown()
        results["_host"]["load_post_suite"] = [
            round(x, 2) for x in os.getloadavg()]
        print(json.dumps(results))
        return results

    def _burst():
        resps = [handle.remote(None) for _ in range(BATCH)]
        for r in resps:
            r.result(timeout=120)

    _timeit("serve_handle_batch_per_s", _burst, BATCH, results=results)

    # HTTP plane: aiohttp proxy -> router -> replica.
    import requests

    addr = serve.get_proxy_address()
    base = f"http://{addr['host']}:{addr['port']}/noop"
    sess = requests.Session()
    assert sess.get(base, timeout=30).status_code == 200
    _timeit("serve_http_rps",
            lambda: sess.get(base, timeout=30), 1, results=results)

    # Concurrent HTTP: a few client threads keep the proxy loop busy
    # (the reference's microbenchmark drives HTTP with many clients).
    import concurrent.futures as cf

    pool = cf.ThreadPoolExecutor(4)
    sessions = [requests.Session() for _ in range(4)]
    for s in sessions:
        s.get(base, timeout=30)

    def _client(s):
        for _ in range(BATCH // 4):
            assert s.get(base, timeout=60).status_code == 200

    def _http_burst():
        # One session PER thread — a requests.Session isn't
        # thread-safe, and sharing one would serialize on its
        # connection pool instead of exercising proxy concurrency.
        futs = [pool.submit(_client, s) for s in sessions]
        for f in futs:
            f.result()

    _timeit("serve_http_concurrent_rps", _http_burst, BATCH,
            results=results)
    pool.shutdown()

    # Overhead decomposition (medians).
    floor = results["direct_actor_calls_per_s"]["median"]
    hnd = results["serve_handle_calls_per_s"]["median"]
    http = results["serve_http_rps"]["median"]
    results["_overhead_ms"] = {
        "direct_actor_call": round(1e3 / floor, 3),
        "handle_call": round(1e3 / hnd, 3),
        "http_call": round(1e3 / http, 3),
        "serve_layer_added": round(1e3 / hnd - 1e3 / floor, 3),
        "proxy_hop_added": round(1e3 / http - 1e3 / hnd, 3),
    }

    serve.shutdown()
    ray_tpu.shutdown()
    results["_host"]["load_post_suite"] = [round(x, 2)
                                           for x in os.getloadavg()]
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    import sys
    res = main(probe="--probe" in sys.argv)
    if "--json-out" in sys.argv:
        with open(sys.argv[sys.argv.index("--json-out") + 1], "w") as f:
            json.dump(res, f)
