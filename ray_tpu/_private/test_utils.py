"""Test utilities: fault injection for chaos testing.

Reference: python/ray/_private/test_utils.py:1098 (NodeKillerActor) and
release/nightly_tests/setup_chaos.py — kill nodes on a cadence while a
real workload runs, asserting the job still completes.  Here the killer
drives the in-process Cluster fixture directly.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional


class NodeKiller:
    """Kills random non-head cluster nodes every interval_s until
    stopped.  Runs in a thread beside the driver (the in-process Cluster
    owns all raylets, so no remote actor is needed)."""

    def __init__(self, cluster, interval_s: float = 3.0,
                 max_kills: int = 1000,
                 node_filter: Optional[Callable] = None,
                 replace: bool = False, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.node_filter = node_filter
        self.replace = replace
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _candidates(self):
        out = []
        for node in self.cluster.nodes:
            if node is self.cluster.head:
                continue
            if self.node_filter is not None and not self.node_filter(node):
                continue
            out.append(node)
        return out

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if len(self.killed) >= self.max_kills:
                return
            targets = self._candidates()
            if not targets:
                continue
            victim = self._rng.choice(targets)
            spec = {"num_cpus": int(victim.raylet.total_resources.get(
                        "CPU", 1)),
                    "resources": {
                        k: v for k, v in
                        victim.raylet.total_resources.items()
                        if k != "CPU"}}
            self.killed.append(victim.raylet.node_id.hex())
            self.cluster.remove_node(victim)
            if self.replace:
                self.cluster.add_node(**spec)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="node-killer")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
