"""Test utilities: fault injection for chaos testing.

Reference: python/ray/_private/test_utils.py:1098 (NodeKillerActor) and
release/nightly_tests/setup_chaos.py — kill nodes on a cadence while a
real workload runs, asserting the job still completes.  Here the killer
drives the in-process Cluster fixture directly.

Beyond whole-process kills, :func:`partition` / :func:`heal` /
:func:`slow_link` drive the message-level fault plane
(ray_tpu._private.failpoints): they install connection rules matched
against the node tags embedded in connection names ("raylet:<id8>->gcs",
"raylet:<id8>->raylet:<id8>"), so a link between two IN-PROCESS cluster
members can be cut, made one-way (half-open), or slowed without killing
anything.  Every TCP link has exactly one client end, and the client end
carries both endpoint tags in its name — so filtering only client-end
connections controls both directions of every link.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional

from ray_tpu._private import failpoints


def node_tag(node) -> str:
    """The fault-plane tag of a cluster member: ``"gcs"`` for the head
    control plane (or the literal string), else ``"raylet:<id8>"``.
    Accepts an InProcessNode, a Raylet, a NodeID/bytes, or a tag."""
    if isinstance(node, str):
        return node
    raylet = getattr(node, "raylet", None)
    if raylet is not None:
        node = raylet
    nid = getattr(node, "node_id", node)
    h = getattr(nid, "hex", None)
    return f"raylet:{h()[:8]}" if callable(h) else str(nid)


def partition(a, b, one_way: bool = False):
    """Cut the link between cluster members ``a`` and ``b`` (either may
    be ``"gcs"``).  ``one_way=True`` drops only a→b traffic — the
    half-open case: b still reaches a, a's frames to b vanish.  Frames
    are dropped at the fault filter, so from both runtimes' point of
    view the link is silently black-holing — exactly what keepalive
    probes and request deadlines exist to detect."""
    ta, tb = node_tag(a), node_tag(b)
    # Client conns a→b carry "ta->…tb": a's outbound frames drop there.
    failpoints.add_conn_rule((f"{ta}->", f"->{tb}"), drop_tx=True,
                             **({} if one_way else {"drop_rx": True}))
    # a→b traffic arriving over b-initiated conns is b's INBOUND side.
    failpoints.add_conn_rule((f"{tb}->", f"->{ta}"), drop_rx=True,
                             **({} if one_way else {"drop_tx": True}))


def slow_link(a, b, delay_s: float):
    """Add ``delay_s`` of one-way latency on every frame between ``a``
    and ``b`` (both directions), preserving frame order."""
    ta, tb = node_tag(a), node_tag(b)
    failpoints.add_conn_rule((f"{ta}->", f"->{tb}"),
                             delay_tx_s=delay_s, delay_rx_s=delay_s)
    failpoints.add_conn_rule((f"{tb}->", f"->{ta}"),
                             delay_tx_s=delay_s, delay_rx_s=delay_s)


def heal():
    """Remove every partition / slow-link rule installed in this
    process (named failpoints are untouched — clear those with
    failpoints.configure(""))."""
    failpoints.clear_conn_rules()


class NodeKiller:
    """Kills random non-head cluster nodes every interval_s until
    stopped.  Runs in a thread beside the driver (the in-process Cluster
    owns all raylets, so no remote actor is needed)."""

    def __init__(self, cluster, interval_s: float = 3.0,
                 max_kills: int = 1000,
                 node_filter: Optional[Callable] = None,
                 replace: bool = False, seed: int = 0):
        self.cluster = cluster
        self.interval_s = interval_s
        self.max_kills = max_kills
        self.node_filter = node_filter
        self.replace = replace
        self.killed: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _candidates(self):
        out = []
        for node in self.cluster.nodes:
            if node is self.cluster.head:
                continue
            if self.node_filter is not None and not self.node_filter(node):
                continue
            out.append(node)
        return out

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if len(self.killed) >= self.max_kills:
                return
            targets = self._candidates()
            if not targets:
                continue
            victim = self._rng.choice(targets)
            spec = {"num_cpus": int(victim.raylet.total_resources.get(
                        "CPU", 1)),
                    "resources": {
                        k: v for k, v in
                        victim.raylet.total_resources.items()
                        if k != "CPU"}}
            self.killed.append(victim.raylet.node_id.hex())
            self.cluster.remove_node(victim)
            if self.replace:
                self.cluster.add_node(**spec)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="node-killer")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
