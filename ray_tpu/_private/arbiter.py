"""Cluster autopilot: an SLO-driven resource arbiter.

One cluster, three tenant classes that previously raced for nodes:

  * **serve** deployments (PR 10's gauge-driven autoscaler) declare a
    p99 TTFT SLO and a priority;
  * **train** gangs (PR 13's elastic re-form path) declare an
    ``elastic_min_workers`` floor and a priority;
  * **data** jobs (PR 11's streaming executor) declare a soak class —
    they want whatever is idle and promise to give it back.

The broker lives inside the GCS (see ``gcs.py``); this module holds the
*policy* — a pure, deterministic state machine with an injectable clock
so the arbitration logic is testable in isolation with seeded demand
traces — plus the client-side helpers (report loop, revocable data
lease) that workloads embed.

Units are CPU slots: the GCS feeds ``tick()`` the cluster's aggregate
CPU total, and one unit backs one serve replica / train worker / data
task slot (the bench provisions 1-CPU nodes so units == nodes).

Decision semantics
------------------
``tick(now, capacity)`` returns a list of decision dicts::

    {"wid": str, "action": "grant"|"revoke", "from": int, "to": int,
     "reason": str, "grace_s": float?}

A *grant* raises a workload's budget, a *revoke* lowers it.  Revokes of
data leases carry ``grace_s``: new admission stops immediately, in-
flight tasks get the grace window to drain.  The policy never directs a
train gang below its declared floor, and two voluntary budget changes
for the same workload are always >= the cooldown apart; only a capacity
crunch (node death making the current grants infeasible) bypasses the
cooldown, and even then trains hold their floor.

Allocation order per tick (which is what makes the recovery ordering
"grow the gang before data re-soaks" structural rather than tuned):

  1. serve floors, then train floors (min_replicas / quorum);
  2. trains up to their full declared size;
  3. serve demand beyond its floor from the remaining free pool;
  4. if a serve SLO breach has been *sustained* past the breach window,
     reclaim from trains — lowest priority first, never below floor;
  5. data soaks whatever is left with revocable leases.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.config import GLOBAL_CONFIG as cfg

SERVE = "serve"
TRAIN = "train"
DATA = "data"
_KINDS = (SERVE, TRAIN, DATA)


class _Workload:
    __slots__ = (
        "wid", "kind", "priority", "min_units", "max_units", "slo",
        "want", "units_now", "granted", "ewma", "breach_since",
        "ok_since", "breached", "last_change_t", "last_report_t",
        "directive", "ever_granted",
    )

    def __init__(self, wid: str, kind: str):
        self.wid = wid
        self.kind = kind
        self.priority = 100
        self.min_units = 0
        self.max_units: Optional[int] = None
        self.slo: Optional[float] = None
        self.want = 0
        self.units_now = 0
        self.granted = 0
        self.ewma: Dict[str, float] = {}
        self.breach_since: Optional[float] = None
        self.ok_since: Optional[float] = None
        self.breached = False
        self.last_change_t = -1e18
        self.last_report_t = -1e18
        # One-shot operator directive (rt resize <gang> <n>) delivered
        # through the next report reply.
        self.directive: Optional[int] = None
        self.ever_granted = False

    def desired(self) -> int:
        d = max(self.want, self.min_units)
        if self.max_units is not None:
            d = min(d, self.max_units)
        return max(d, 0)

    def view(self) -> Dict[str, Any]:
        return {
            "wid": self.wid, "kind": self.kind,
            "priority": self.priority, "min_units": self.min_units,
            "max_units": self.max_units, "slo": self.slo,
            "want": self.want, "units_now": self.units_now,
            "granted": self.granted, "breached": self.breached,
            "signals": dict(self.ewma),
        }


class ArbiterPolicy:
    """The pure arbitration state machine.

    No asyncio, no RPC, no global clock: ``clock`` is injectable and
    every entry point takes/derives an explicit ``now`` so tests drive
    it with a fake clock and seeded demand traces.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic, *,
                 period_s: Optional[float] = None,
                 breach_window_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 ewma_alpha: Optional[float] = None,
                 revoke_grace_s: Optional[float] = None,
                 stale_report_s: Optional[float] = None):
        self._clock = clock
        self.period_s = (cfg.autopilot_period_s
                         if period_s is None else period_s)
        self.breach_window_s = (cfg.autopilot_slo_breach_window_s
                                if breach_window_s is None
                                else breach_window_s)
        self.cooldown_s = (cfg.autopilot_cooldown_s
                           if cooldown_s is None else cooldown_s)
        self.ewma_alpha = (cfg.autopilot_ewma_alpha
                           if ewma_alpha is None else ewma_alpha)
        self.revoke_grace_s = (cfg.autopilot_data_revoke_grace_s
                               if revoke_grace_s is None
                               else revoke_grace_s)
        self.stale_report_s = (cfg.autopilot_stale_report_s
                               if stale_report_s is None
                               else stale_report_s)
        self._workloads: Dict[str, _Workload] = {}
        self._last_tick_t: Optional[float] = None
        # Cumulative counters mirrored into prometheus by the GCS.
        self.grants_total = 0
        self.revocations_total = 0
        self.slo_breach_seconds = 0.0

    # ------------------------------------------------------- registry
    def register(self, wid: str, kind: str, *, priority: int = 100,
                 min_units: int = 0, max_units: Optional[int] = None,
                 slo: Optional[float] = None,
                 now: Optional[float] = None) -> _Workload:
        if kind not in _KINDS:
            raise ValueError(f"unknown workload kind {kind!r} "
                             f"(expected one of {_KINDS})")
        wl = self._workloads.get(wid)
        if wl is None or wl.kind != kind:
            wl = _Workload(wid, kind)
            self._workloads[wid] = wl
        wl.priority = int(priority)
        wl.min_units = max(int(min_units), 0)
        wl.max_units = None if max_units is None else int(max_units)
        wl.slo = slo
        wl.last_report_t = self._clock() if now is None else now
        return wl

    def unregister(self, wid: str) -> bool:
        return self._workloads.pop(wid, None) is not None

    def get(self, wid: str) -> Optional[_Workload]:
        return self._workloads.get(wid)

    def report(self, wid: str, *, want: int, units_now: int,
               signals: Optional[Dict[str, float]] = None,
               now: Optional[float] = None,
               **decl: Any) -> Dict[str, Any]:
        """Ingest one workload report; returns the current grant.

        A report doubles as a registration upsert when ``decl`` carries
        the declaration fields (kind/priority/min_units/max_units/slo).
        That is what makes a GCS restart safe by construction: broker
        state is deliberately NOT in the snapshot, so a restarted GCS
        starts with zero grants and rebuilds the whole table within one
        report period — stale grants cannot be resurrected.
        """
        now = self._clock() if now is None else now
        wl = self._workloads.get(wid)
        if wl is None:
            kind = decl.get("kind")
            if kind is None:
                return {"ok": False, "error": {
                    "code": "UNKNOWN_WORKLOAD",
                    "message": f"workload {wid!r} is not registered and "
                               f"the report carries no declaration"}}
            wl = self.register(
                wid, kind, priority=decl.get("priority", 100),
                min_units=decl.get("min_units", 0),
                max_units=decl.get("max_units"),
                slo=decl.get("slo"), now=now)
        elif decl.get("kind"):
            self.register(
                wid, decl["kind"],
                priority=decl.get("priority", wl.priority),
                min_units=decl.get("min_units", wl.min_units),
                max_units=decl.get("max_units", wl.max_units),
                slo=decl.get("slo", wl.slo), now=now)
            wl = self._workloads[wid]
        wl.want = max(int(want), 0)
        wl.units_now = max(int(units_now), 0)
        wl.last_report_t = now
        alpha = min(max(self.ewma_alpha, 0.0), 1.0)
        for key, val in (signals or {}).items():
            try:
                val = float(val)
            except (TypeError, ValueError):
                continue
            prev = wl.ewma.get(key)
            wl.ewma[key] = (val if prev is None or alpha >= 1.0
                            else alpha * val + (1 - alpha) * prev)
        directive, wl.directive = wl.directive, None
        return {"ok": True, "granted": wl.granted,
                "directive": directive,
                "revoke_grace_s": self.revoke_grace_s,
                "report_period_s": cfg.autopilot_report_period_s}

    def set_directive(self, wid: str, target: int) -> None:
        wl = self._workloads[wid]
        wl.directive = int(target)

    # ----------------------------------------------------- arbitration
    def _update_breach(self, wl: _Workload, now: float,
                       dt: float) -> None:
        sig = wl.ewma.get("ttft_p99_s")
        if wl.slo is None or sig is None:
            wl.breach_since = wl.ok_since = None
            wl.breached = False
            return
        if sig > wl.slo:
            self.slo_breach_seconds += dt
            wl.ok_since = None
            if wl.breach_since is None:
                wl.breach_since = now
            if now - wl.breach_since >= self.breach_window_s:
                wl.breached = True
        else:
            wl.breach_since = None
            if wl.ok_since is None:
                wl.ok_since = now
            if now - wl.ok_since >= self.breach_window_s:
                wl.breached = False

    def tick(self, now: Optional[float] = None,
             capacity: int = 0) -> List[Dict[str, Any]]:
        now = self._clock() if now is None else now
        dt = (0.0 if self._last_tick_t is None
              else max(now - self._last_tick_t, 0.0))
        self._last_tick_t = now

        # Drop workloads whose client stopped reporting (driver died
        # without unregistering) — their budget returns to the pool.
        for wid in [w.wid for w in self._workloads.values()
                    if now - w.last_report_t > self.stale_report_s]:
            del self._workloads[wid]

        by_kind: Dict[str, List[_Workload]] = {k: [] for k in _KINDS}
        for wl in self._workloads.values():
            by_kind[wl.kind].append(wl)
        for k in by_kind:
            # Priority desc, then wid for determinism.
            by_kind[k].sort(key=lambda w: (-w.priority, w.wid))
        serves, trains, datas = (by_kind[SERVE], by_kind[TRAIN],
                                 by_kind[DATA])

        for wl in serves:
            self._update_breach(wl, now, dt)

        target: Dict[str, int] = {w: 0 for w in self._workloads}
        pool = max(int(capacity), 0)

        def _take(wl: _Workload, n: int) -> None:
            nonlocal pool
            n = max(min(n, pool), 0)
            target[wl.wid] += n
            pool -= n

        # 1. Floors: serve min_replicas, then train quorum floors.
        # Floors are granted even if the pool runs dry (capacity
        # accounting is advisory; a gang is never *directed* below its
        # floor by the arbiter — that is the quorum-safety invariant).
        for wl in serves + trains:
            floor = min(wl.min_units, wl.desired())
            target[wl.wid] = floor
            pool = max(pool - floor, 0)
        # 2. Trains up to their full declared size.
        for wl in trains:
            _take(wl, wl.desired() - target[wl.wid])
        # 3. Serve demand beyond floor from the free pool.
        for wl in serves:
            _take(wl, wl.desired() - target[wl.wid])
        # 4. Sustained SLO breach -> reclaim from trains, lowest
        #    priority first, never below floor.
        shortfall = sum(wl.desired() - target[wl.wid] for wl in serves
                        if wl.breached)
        if shortfall > 0:
            for victim in sorted(trains,
                                 key=lambda w: (w.priority, w.wid)):
                if shortfall <= 0:
                    break
                spare = target[victim.wid] - victim.min_units
                take = max(min(spare, shortfall), 0)
                if take <= 0:
                    continue
                target[victim.wid] -= take
                shortfall -= take
                recovered = take
                for wl in serves:
                    if not wl.breached or recovered <= 0:
                        continue
                    add = min(wl.desired() - target[wl.wid], recovered)
                    if add > 0:
                        target[wl.wid] += add
                        recovered -= add
        # 5. Data soaks the remainder with revocable leases — but only
        #    truly IDLE capacity.  Headroom an under-allocated train is
        #    entitled to stays reserved: after a reclaim, the gang's
        #    revoke cooldown can expire a tick later than data's, and
        #    without the reservation a freed slot would re-soak into
        #    data one tick before the gang is allowed to grow back.
        #    "Grow before data re-soaks" is a structural invariant, not
        #    a cooldown race.
        def _will_pin(wl: _Workload) -> bool:
            return (wl.ever_granted and target[wl.wid] != wl.granted
                    and now - wl.last_change_t < self.cooldown_s)

        train_deficit = sum(
            max(wl.desired() - (wl.granted if _will_pin(wl)
                                else target[wl.wid]), 0)
            for wl in trains)
        pool = max(pool - train_deficit, 0)
        for wl in datas:
            _take(wl, wl.desired())

        # Cooldown pinning: a workload inside its cooldown keeps its
        # current grant — unless the pinned total is infeasible (node
        # death shrank capacity, or a pin re-inflated a grant past what
        # the phases allotted), in which case the crunch overrides the
        # cooldown, data first, trains still never below floor.  The
        # shave considers EVERY workload, not just pinned ones: a
        # fresh phase-5 data grant must be the first thing to give
        # back, or an over-commit caused by someone ELSE's pin would
        # be taken out of a train's hide while data keeps the slot.
        for wl in self._workloads.values():
            t = target[wl.wid]
            if (wl.ever_granted and t != wl.granted
                    and now - wl.last_change_t < self.cooldown_s):
                target[wl.wid] = wl.granted
        over = sum(target.values()) - max(int(capacity), 0)
        if over > 0:
            for wl in sorted(self._workloads.values(),
                             key=lambda w: (_KINDS.index(w.kind) * -1,
                                            w.priority, w.wid)):
                if over <= 0:
                    break
                floor = wl.min_units if wl.kind != DATA else 0
                give = min(max(target[wl.wid] - floor, 0), over)
                if give > 0:
                    target[wl.wid] -= give
                    over -= give

        decisions: List[Dict[str, Any]] = []
        for wl in self._workloads.values():
            t = target[wl.wid]
            if t == wl.granted and wl.ever_granted:
                continue
            action = ("grant" if t > wl.granted or not wl.ever_granted
                      else "revoke")
            reason = "alloc"
            if action == "revoke":
                if wl.kind == TRAIN:
                    reason = "serve_slo_breach"
                elif wl.kind == DATA:
                    reason = "reclaimed"
                else:
                    reason = "demand_drop"
            elif wl.kind == SERVE and wl.breached:
                reason = "slo_breach_upscale"
            dec = {"wid": wl.wid, "kind": wl.kind, "action": action,
                   "from": wl.granted, "to": t, "reason": reason}
            if action == "revoke" and wl.kind == DATA:
                dec["grace_s"] = self.revoke_grace_s
            if action == "grant":
                self.grants_total += 1
            else:
                self.revocations_total += 1
            wl.granted = t
            wl.ever_granted = True
            wl.last_change_t = now
            decisions.append(dec)
        return decisions

    # --------------------------------------------------------- export
    def status(self) -> Dict[str, Any]:
        return {
            "workloads": [w.view() for w in self._workloads.values()],
            "grants_total": self.grants_total,
            "revocations_total": self.revocations_total,
            "slo_breach_seconds": self.slo_breach_seconds,
        }


# ---------------------------------------------------------------------
# Client side: report loop + revocable data lease.
# ---------------------------------------------------------------------

def gcs_call(method: str, body: Dict[str, Any],
             timeout: Optional[float] = None) -> Any:
    """Synchronous GCS RPC usable from any thread (controller executor
    threads, gang agent threads, the CLI)."""
    from ray_tpu._private.worker import global_worker
    return global_worker.gcs_call(method, body, timeout=timeout)


class DataLease:
    """A revocable soak lease for a streaming data job.

    ``allowed()`` is the number of concurrently admitted tasks the
    broker currently grants.  A background reporter thread refreshes
    the grant every ``cfg.autopilot_report_period_s``; when the broker
    revokes units, new admission drops *immediately* (the operator's
    admission loop consults ``allowed()`` before launching every task)
    while in-flight tasks get ``revoke_grace_s`` to drain — that is the
    clean-backpressure contract the arbiter relies on.
    """

    def __init__(self, wid: str, *, want: int = 1 << 16,
                 priority: int = 0, start: bool = True):
        self.wid = wid
        self.want = want
        self.priority = priority
        self._granted = 0
        self._in_flight = 0
        self._revoked_t: Optional[float] = None
        self._grace_s = cfg.autopilot_data_revoke_grace_s
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- grant side -----------------------------------------------------
    def allowed(self) -> int:
        with self._lock:
            return self._granted

    def note_launched(self, n: int = 1) -> None:
        with self._lock:
            self._in_flight += n

    def note_finished(self, n: int = 1) -> None:
        with self._lock:
            self._in_flight = max(self._in_flight - n, 0)

    @property
    def revoked_at(self) -> Optional[float]:
        with self._lock:
            return self._revoked_t

    def _apply_reply(self, reply: Dict[str, Any]) -> None:
        if not isinstance(reply, dict) or not reply.get("ok", False):
            return
        granted = int(reply.get("granted", 0))
        with self._lock:
            if granted < self._granted:
                self._revoked_t = time.monotonic()
            elif granted > self._granted:
                self._revoked_t = None
            self._granted = granted
            self._grace_s = float(
                reply.get("revoke_grace_s", self._grace_s))

    def report_once(self) -> None:
        with self._lock:
            in_flight = self._in_flight
        reply = gcs_call("arbiter_report", {
            "wid": self.wid, "want": self.want,
            "units_now": in_flight,
            "decl": {"kind": DATA, "priority": self.priority,
                     "min_units": 0},
        })
        self._apply_reply(reply)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        try:
            self.report_once()
        except Exception:
            pass
        self._thread = threading.Thread(
            target=self._report_loop, daemon=True,
            name=f"rt-data-lease-{self.wid}")
        self._thread.start()

    def _report_loop(self) -> None:
        while not self._stop.wait(cfg.autopilot_report_period_s):
            try:
                self.report_once()
            except Exception:
                # GCS unreachable: keep the last grant; the broker will
                # age us out via the stale-report TTL if we never come
                # back, so holding the grant here cannot leak budget.
                continue

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            gcs_call("arbiter_unregister", {"wid": self.wid})
        except Exception:
            pass


_AMBIENT_LEASE: Optional[DataLease] = None


def set_ambient_data_lease(lease: Optional[DataLease]) -> None:
    """Install a process-wide lease consulted by streaming operators
    that were not handed one explicitly."""
    global _AMBIENT_LEASE
    _AMBIENT_LEASE = lease


def ambient_data_lease() -> Optional[DataLease]:
    return _AMBIENT_LEASE
