"""Per-node hardware reporter.

Reference: dashboard/modules/reporter/reporter_agent.py:12,42 — each
node's dashboard agent samples psutil/gpustat and relays utilization to
the metrics path.  Here the reporter runs inside the per-node raylet
process (the raylet IS per-node in the real process topology), samples
cpu/mem/disk (+ object-store occupancy and TPU resource presence), and
ships the snapshot to the GCS on the heartbeat channel, where the state
API, `rt status`, and the dashboard read it.
"""

from __future__ import annotations

import os
import time


def sample_node_stats(session_dir: str | None = None,
                      store=None, store_capacity: int = 0,
                      n_workers: int = 0) -> dict:
    """One hardware snapshot.  psutil when available; /proc fallback."""
    out: dict = {"ts": time.time(), "pid": os.getpid(),
                 "workers": n_workers}
    try:
        import psutil
        out["cpu_percent"] = psutil.cpu_percent(interval=None)
        out["cpu_count"] = psutil.cpu_count()
        vm = psutil.virtual_memory()
        out["mem_total"] = int(vm.total)
        out["mem_used"] = int(vm.total - vm.available)
        out["mem_percent"] = float(vm.percent)
        la = os.getloadavg()
        out["load_avg_1m"] = round(la[0], 2)
    except Exception:
        try:
            la = os.getloadavg()
            out["load_avg_1m"] = round(la[0], 2)
        except OSError:
            pass
    try:
        import shutil
        du = shutil.disk_usage(session_dir or "/tmp")
        out["disk_total"] = int(du.total)
        out["disk_used"] = int(du.used)
        out["disk_percent"] = round(100.0 * du.used / max(du.total, 1), 1)
    except Exception:
        pass
    if store is not None and store_capacity:
        try:
            st = store.stats()
            out["object_store_used"] = int(st["used"])
            out["object_store_capacity"] = int(store_capacity)
            out["object_store_pinned"] = int(st["pinned_bytes"])
        except Exception:
            pass
    return out


def format_utilization(stats: dict | None) -> str:
    """One-line human rendering for `rt status` (empty when absent)."""
    if not stats:
        return ""
    parts = []
    if "cpu_percent" in stats:
        parts.append(f"cpu {stats['cpu_percent']:.0f}%")
    if "mem_percent" in stats:
        parts.append(f"mem {stats['mem_percent']:.0f}%")
    if "object_store_used" in stats and stats.get("object_store_capacity"):
        pct = 100.0 * stats["object_store_used"] / \
            stats["object_store_capacity"]
        parts.append(f"store {pct:.0f}%")
    if "disk_percent" in stats:
        parts.append(f"disk {stats['disk_percent']:.0f}%")
    if "workers" in stats:
        parts.append(f"workers {stats['workers']}")
    return " ".join(parts)
