"""Local mode: the whole API surface executed inline in one process.

Reference: ray.init(local_mode=True) (python/ray/_private/worker.py) and
the C++ mock layer (src/mock/ray) — a runtime-free seam for debugging
user code (breakpoints work, stack traces are local, no worker spawn
latency) and for unit tests that don't want a cluster.  Tasks run
synchronously at submission; actors are plain objects; the object store
is a dict.  GCS-backed verbs (nodes, placement groups, named actors
across processes) raise a clear error.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Callable, Dict, Tuple

from ray_tpu import exceptions as rexc
from ray_tpu._private import locksan
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef


class _ExecCtx:
    task_id = None


def _resolve(obj, store, errors):
    """Replace TOP-LEVEL ObjectRef args with their stored values (the
    real runtime's semantics: nested refs inside containers stay refs
    and resolve via get/await).  A ref whose task failed re-raises the
    original exception (matching the runtime: a failed dependency
    propagates the underlying task error to the consumer)."""
    def _lookup(ref):
        if ref.id in errors:
            raise errors[ref.id]
        return store[ref.id]

    if isinstance(obj, ObjectRef):
        return _lookup(obj)
    if isinstance(obj, list):
        return [_lookup(o) if isinstance(o, ObjectRef) else o
                for o in obj]
    if isinstance(obj, dict):
        return {k: _lookup(v) if isinstance(v, ObjectRef) else v
                for k, v in obj.items()}
    return obj


class _Stored:
    """Either a value or a captured exception (re-raised at get)."""

    __slots__ = ("value", "error")

    def __init__(self, value=None, error=None):
        self.value = value
        self.error = error


class LocalModeWorker:
    """Duck-type of CoreWorker for the verbs the public API uses."""

    mode = "local"
    connected = True

    def __init__(self, namespace: str = "default"):
        self.namespace = namespace
        self._store: Dict[ObjectID, Any] = {}
        self._errors: Dict[ObjectID, Exception] = {}
        self._functions: Dict[bytes, Callable] = {}
        self._actors: Dict[ActorID, Any] = {}
        self._named: Dict[Tuple[str, str], ActorID] = {}
        self._actor_meta: Dict[ActorID, str] = {}
        self._lock = locksan.make_rlock("LocalModeWorker._lock")
        # RuntimeContext surface (api.get_runtime_context reads these).
        self.job_id = JobID.from_random()
        self.worker_id = None
        self.node_id = NodeID.from_random()
        self.actor_id = None
        self.exec_ctx = _ExecCtx()

    # ------------------------------------------------------------ store
    def put(self, value) -> ObjectRef:
        oid = ObjectID.from_random()
        with self._lock:
            self._store[oid] = value
        return ObjectRef(oid)

    def _store_result(self, value, error=None):
        oid = ObjectID.from_random()
        with self._lock:
            if error is not None:
                self._errors[oid] = error
            else:
                self._store[oid] = value
        return ObjectRef(oid)

    def get(self, refs, *, timeout=None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        out = []
        with self._lock:
            for r in refs:
                if r.id in self._errors:
                    raise self._errors[r.id]
                if r.id not in self._store:
                    raise rexc.ObjectLostError(
                        r.hex(), "unknown object in local mode")
                out.append(self._store[r.id])
        return out[0] if single else out

    def wait(self, refs, *, num_returns=1, timeout=None,
             fetch_local=True):
        # Everything is materialized at submission in local mode.
        return refs[:num_returns], refs[num_returns:]

    # ------------------------------------------------------------ tasks
    def export_function(self, fn) -> bytes:
        fn_id = uuid.uuid4().bytes
        self._functions[fn_id] = fn
        return fn_id

    def submit_task(self, fn_id: bytes, args, kwargs, opts: dict):
        fn = self._functions[fn_id]
        num_returns = opts.get("num_returns", 1)
        try:
            with self._lock:
                args = _resolve(list(args), self._store, self._errors)
                kwargs = _resolve(dict(kwargs), self._store, self._errors)
            result = fn(*args, **kwargs)
            err = None
        except Exception as e:
            result, err = None, e
        if err is not None or num_returns == 1:
            refs = [self._store_result(result, err)]
            if num_returns != 1:
                refs = refs * num_returns
            return refs
        if num_returns == 0:
            return []
        vals = list(result)
        if len(vals) != num_returns:
            raise ValueError(f"task returned {len(vals)} values, "
                             f"expected {num_returns}")
        return [self._store_result(v) for v in vals]

    def cancel_task(self, ref, force: bool = False) -> bool:
        return False  # tasks finish at submission; nothing to cancel

    # ----------------------------------------------------------- actors
    def create_actor(self, class_id: bytes, init_args, init_kwargs,
                     opts: dict) -> ActorID:
        cls = self._functions[class_id]
        with self._lock:
            init_args = _resolve(list(init_args), self._store, self._errors)
            init_kwargs = _resolve(dict(init_kwargs), self._store, self._errors)
        instance = cls(*init_args, **init_kwargs)
        actor_id = ActorID.from_random()
        self._actors[actor_id] = instance
        self._actor_meta[actor_id] = opts.get("class_name",
                                              cls.__name__)
        name = opts.get("name")
        if name:
            self._named[(opts.get("namespace", self.namespace),
                         name)] = actor_id
        return actor_id

    def submit_actor_task(self, actor_id, actor_addr, method, args,
                          kwargs, num_returns=1, opts=None):
        instance = self._actors.get(actor_id)
        if instance is None:
            raise rexc.ActorDiedError(actor_id, "actor killed "
                                                "(local mode)")
        try:
            with self._lock:
                args = _resolve(list(args), self._store, self._errors)
                kwargs = _resolve(dict(kwargs), self._store, self._errors)
            bound = getattr(instance, method)
            result = bound(*args, **kwargs)
            import inspect
            if inspect.iscoroutine(result):
                import asyncio
                result = asyncio.new_event_loop().run_until_complete(
                    result)
            err = None
        except rexc.ActorDiedError:
            raise
        except Exception as e:
            result, err = None, e
        if err is not None or num_returns == 1:
            refs = [self._store_result(result, err)]
            if num_returns not in (0, 1):
                refs = refs * num_returns  # same error at every position
            return refs
        vals = list(result)
        if len(vals) != num_returns:
            raise ValueError(f"actor method returned {len(vals)} values, "
                             f"expected {num_returns}")
        return [self._store_result(v) for v in vals]

    async def get_async(self, ref):
        """`await ref` inside async methods: the value is already local."""
        return self.get(ref)

    def kill_actor_local(self, actor_id):
        self._actors.pop(actor_id, None)
        for key, aid in list(self._named.items()):
            if aid == actor_id:
                del self._named[key]

    def get_named_actor(self, name: str, namespace: str):
        aid = self._named.get((namespace, name))
        if aid is None or aid not in self._actors:
            return None
        return {"actor_id": aid,
                "class_name": self._actor_meta.get(aid, ""),
                "addr": None}

    # ------------------------------------------------------- lifecycle
    def shutdown(self):
        with self._lock:
            self._store.clear()
            self._actors.clear()
            self._named.clear()

    def _unsupported(self, what: str):
        raise RuntimeError(
            f"{what} is not available in local mode "
            f"(ray_tpu.init(local_mode=True) runs everything inline "
            f"in this process); start a real cluster for it")

    def _gcs_request(self, method, body=None):
        self._unsupported(f"GCS rpc {method!r}")

    def _run(self, coro):
        self._unsupported("runtime coroutines")
