"""ObjectRef: a first-class future/handle to an object in the cluster.

Mirrors the reference's ObjectRef semantics (reference:
python/ray/includes/object_ref.pxi; ownership described in
src/ray/core_worker/reference_count.h:61): every object has an *owner* (the
process that created it); the ref carries the object id plus the owner's
address so any holder can locate and fetch the value.
"""

from __future__ import annotations

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_track", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_addr: tuple[str, int] | None = None,
                 _track: bool = False):
        self.id = object_id
        self.owner_addr = owner_addr
        # Only the instance handed to the user at creation time carries a
        # local-refcount stake; pickled/copied views don't double count.
        self._track = _track

    def __del__(self):
        if getattr(self, "_track", False):
            try:
                from ray_tpu._private import worker as _w
                if _w.global_worker is not None:
                    _w.global_worker.remove_local_ref(self)
            except Exception:
                pass

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Serialization context (if any) tracks nested refs for borrowing.
        ctx = _SER_CTX.get()
        if ctx is not None:
            ctx.append(self)
        return (ObjectRef, (self.id, self.owner_addr))

    # Allow `await ref` inside async actors.
    def __await__(self):
        from ray_tpu._private import worker as _w
        return _w.global_worker.get_async(self).__await__()

    def future(self):
        from ray_tpu._private import worker as _w
        return _w.global_worker.get_future(self)


class ObjectRefGenerator:
    """Result of a `num_returns="dynamic"` task: the ObjectRefs of the
    values the generator yielded, in order (reference:
    DynamicObjectRefGenerator — `ray.get` the outer ref, then iterate).

    Lifetime: each deserialized generator adds a local-refcount stake
    for every yielded object in the owner process (released when the
    generator's refs are GC'd), and the outer task ref holds the
    initial registration pin — so the yields live while EITHER the
    outer ref or any fetched generator is alive."""

    def __init__(self, refs):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self):
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]

    def __repr__(self):
        return f"ObjectRefGenerator({len(self._refs)} refs)"

    def __reduce__(self):
        return (_rebuild_ref_generator,
                (tuple((r.id, r.owner_addr) for r in self._refs),))


def _rebuild_ref_generator(states):
    """Unpickle hook: reconstruct the generator with TRACKED refs that
    acquire a stake in the owner's refcount table (no-op in borrower
    processes, whose owned table doesn't hold these ids)."""
    from ray_tpu._private import worker as _w
    w = _w.global_worker
    refs = []
    for oid, addr in states:
        ref = ObjectRef(oid, addr, _track=True)
        if w is not None:
            try:
                w.add_local_ref(ref)
            except Exception:
                pass
        refs.append(ref)
    return ObjectRefGenerator(refs)


import contextvars

_SER_CTX: contextvars.ContextVar[list | None] = contextvars.ContextVar(
    "ray_tpu_ser_ctx", default=None)


class track_nested_refs:
    """Context manager collecting ObjectRefs pickled within its scope."""

    def __init__(self):
        self.refs: list[ObjectRef] = []
        self._token = None

    def __enter__(self):
        self._token = _SER_CTX.set(self.refs)
        return self.refs

    def __exit__(self, *exc):
        _SER_CTX.reset(self._token)
        return False
