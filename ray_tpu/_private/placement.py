"""Bundle placement policies, including TPU ICI-topology-aware packing.

Reference: src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h
implements PACK / SPREAD / STRICT_PACK / STRICT_SPREAD over GPU-era nodes.
The TPU-era addition here: nodes carry ICI mesh coordinates as labels
("tpu_coords": (x, y, z), "tpu_slice": name), and STRICT_SPREAD /
SPREAD placements for TPU bundles prefer *contiguous sub-meshes* so the
collective traffic of a gang-scheduled SPMD job rides ICI instead of DCN.
This is a capability the reference never needed (NCCL rings are
topology-agnostic at scheduling time); on TPU, adjacency is the whole game.
"""

from __future__ import annotations


class PlacementError(Exception):
    pass


def _fits(avail: dict, req: dict) -> bool:
    return all(avail.get(k, 0) >= v for k, v in req.items())


def _sub(avail: dict, req: dict):
    for k, v in req.items():
        avail[k] = avail.get(k, 0) - v


def _is_tpu_bundle(bundle: dict) -> bool:
    return any(k == "TPU" or k.startswith("TPU-") for k in bundle)


def _sort_by_ici(nodes):
    """Order nodes so that consecutive picks are ICI neighbours: group by
    slice, then lexicographic mesh coordinates within a slice."""
    def key(n):
        labels = n.labels or {}
        return (labels.get("tpu_slice", "~"),
                tuple(labels.get("tpu_coords", ())) or (1 << 30,))
    return sorted(nodes, key=key)


def choose_nodes_for_bundles(bundles, strategy, nodes):
    """Pick one node per bundle. Returns list[NodeInfo] aligned to bundles,
    or None if currently infeasible. Raises PlacementError if *never*
    feasible with the given alive nodes."""
    if not nodes:
        return None
    for b in bundles:
        if not any(_fits(n.total_resources, b) for n in nodes):
            raise PlacementError(f"bundle {b} fits no node")

    tpu_gang = any(_is_tpu_bundle(b) for b in bundles)

    if strategy == "STRICT_PACK":
        # Every bundle on ONE node.
        combined: dict = {}
        for b in bundles:
            for k, v in b.items():
                combined[k] = combined.get(k, 0) + v
        for n in sorted(nodes, key=lambda n: -n.load):
            if _fits(n.available_resources, combined):
                return [n] * len(bundles)
        if not any(_fits(n.total_resources, combined) for n in nodes):
            raise PlacementError("STRICT_PACK bundles fit no single node")
        return None

    if strategy == "STRICT_SPREAD":
        # Distinct node per bundle; for TPU gangs pick a contiguous sub-mesh.
        ordered = _sort_by_ici(nodes) if tpu_gang else sorted(
            nodes, key=lambda n: n.load)
        if tpu_gang:
            # Slide a window over the ICI ordering to find a contiguous run
            # of len(bundles) nodes that each fit their bundle.
            k = len(bundles)
            for start in range(len(ordered) - k + 1):
                window = ordered[start:start + k]
                scratch = [dict(n.available_resources) for n in window]
                ok = True
                for b, av in zip(bundles, scratch):
                    if not _fits(av, b):
                        ok = False
                        break
                    _sub(av, b)
                if ok:
                    return window
            return None
        assignment = []
        used = set()
        for b in bundles:
            pick = None
            for n in ordered:
                if id(n) in used:
                    continue
                if _fits(n.available_resources, b):
                    pick = n
                    break
            if pick is None:
                if len(nodes) < len(bundles):
                    raise PlacementError(
                        f"STRICT_SPREAD needs {len(bundles)} nodes, "
                        f"cluster has {len(nodes)}")
                return None
            used.add(id(pick))
            assignment.append(pick)
        return assignment

    # PACK / SPREAD: best-effort. Simulate availability while assigning.
    scratch = {id(n): dict(n.available_resources) for n in nodes}
    if strategy == "SPREAD":
        ordered = _sort_by_ici(nodes) if tpu_gang else sorted(
            nodes, key=lambda n: n.load)
    else:  # PACK: most-loaded first so bundles co-locate
        ordered = sorted(nodes, key=lambda n: -n.load)
    assignment = []
    spread_i = 0
    for b in bundles:
        pick = None
        if strategy == "SPREAD":
            # round-robin over the ordering
            for j in range(len(ordered)):
                n = ordered[(spread_i + j) % len(ordered)]
                if _fits(scratch[id(n)], b):
                    pick = n
                    spread_i = (spread_i + j + 1) % len(ordered)
                    break
        else:
            for n in ordered:
                if _fits(scratch[id(n)], b):
                    pick = n
                    break
        if pick is None:
            return None
        _sub(scratch[id(pick)], b)
        assignment.append(pick)
    return assignment
