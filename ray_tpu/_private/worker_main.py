"""Entry point of a worker process spawned by the raylet.

Reference: python/ray/_private/workers/default_worker.py — connects the
core worker to its raylet + GCS and runs the task loop until told to exit.
"""

from __future__ import annotations

import asyncio
import logging
import os


async def _amain():
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        # The sitecustomize TPU hook overrides JAX_PLATFORMS via jax.config;
        # re-pin cpu so user tasks running jax here never dial the chip
        # tunnel (only "tpu"-kind workers may).
        from ray_tpu._private.jax_utils import ensure_cpu
        ensure_cpu()
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.ids import WorkerID
    from ray_tpu._private.worker import CoreWorker, MODE_WORKER

    gcs_addr = (os.environ["RT_GCS_HOST"], int(os.environ["RT_GCS_PORT"]))
    raylet_addr = (os.environ["RT_RAYLET_HOST"],
                   int(os.environ["RT_RAYLET_PORT"]))
    # Workers advertise their node's address (the raylet's bind host):
    # on multi-host clusters, peers dial workers directly for task push
    # and owner-protocol calls, and loopback would not route.
    host = raylet_addr[0]
    cw = CoreWorker(
        MODE_WORKER,
        gcs_addr,
        raylet_addr=raylet_addr,
        store_path=os.environ.get("RT_STORE_PATH"),
        store_cap=int(os.environ.get("RT_STORE_CAP", "0")) or None,
        worker_id=WorkerID.from_hex(os.environ["RT_WORKER_ID"]),
        host=host,
    )
    worker_mod.global_worker = cw
    await cw.start_worker_async()
    await asyncio.Event().wait()


def main():
    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {os.getpid()}] %(levelname)s %(message)s")
    try:
        asyncio.run(_amain())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
