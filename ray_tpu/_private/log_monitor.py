"""Log monitor: tail worker log files and publish lines to GCS pubsub.

Reference: python/ray/_private/log_monitor.py:100 — LogMonitor tails every
worker log on its node and publishes via GCS pubsub; the driver mirrors
the lines to its own stderr.  Here the monitor is a coroutine inside each
raylet (one per node, like the reference's per-node process).
"""

from __future__ import annotations

import asyncio
import glob
import logging
import os

logger = logging.getLogger(__name__)

MAX_LINES_PER_TICK = 200


class LogMonitor:
    def __init__(self, logs_dir: str, publish, node_id_hex: str):
        """publish: async callable(channel, message)."""
        self.logs_dir = logs_dir
        self.publish = publish
        self.node_id_hex = node_id_hex
        self._offsets: dict[str, int] = {}
        self._stopped = False

    async def run(self, period_s: float = 0.3):
        while not self._stopped:
            try:
                await self.tick()
            except Exception as e:
                logger.debug("log monitor tick failed: %s", e)
            await asyncio.sleep(period_s)

    async def tick(self):
        for path in glob.glob(os.path.join(self.logs_dir, "worker-*.log")):
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(path, 0)
            if size <= off:
                continue
            read_limit = 512 * 1024
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(read_limit)
            except OSError:
                continue
            # Only ship complete lines; carry partials to the next tick.
            # All offset arithmetic stays in RAW bytes (decoding with
            # errors="replace" changes byte counts).
            cut = chunk.rfind(b"\n")
            if cut < 0:
                if len(chunk) >= read_limit:
                    # One line longer than the buffer would wedge this
                    # file forever: ship it truncated and move on.
                    raw_lines = [chunk]
                    consumed = len(chunk)
                else:
                    continue
            else:
                raw_lines = chunk[:cut].split(b"\n")
                if len(raw_lines) > MAX_LINES_PER_TICK:
                    # Cap the batch WITHOUT dropping: advance only past
                    # the lines actually published.
                    raw_lines = raw_lines[:MAX_LINES_PER_TICK]
                    consumed = sum(len(rl) + 1 for rl in raw_lines)
                else:
                    consumed = cut + 1
            self._offsets[path] = off + consumed
            lines = [rl.decode("utf-8", "replace") for rl in raw_lines
                     if rl]
            if not lines:
                continue
            worker = os.path.basename(path)[len("worker-"):-len(".log")]
            await self.publish("logs", {
                "node": self.node_id_hex,
                "worker": worker,
                "lines": lines,
            })

    def stop(self):
        self._stopped = True
